"""Seeded conv/pool config fuzz (VERDICT r4 #2 done-criterion): sample
random configurations across stride x dilation x padding x layout x
kernel x channels and compare against TF / torch — the search space
where orientation and padding-convention bugs (the round-4 deconv flip
class) hide.  Seeds are FIXED, so a pass is reproducible and a failure
pins the exact config.
"""
import os

import numpy as np
import pytest

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.autodiff.ops import OP_TABLE  # noqa: E402

N_CASES = 16


def _conv2d_nhwc_case(rng):
    k = int(rng.randint(1, 4))
    stride = int(rng.randint(1, 3))
    # TF rejects stride > 1 with dilation > 1
    dil = 1 if stride > 1 else int(rng.randint(1, 3))
    padding = ["SAME", "VALID"][rng.randint(2)]
    B, H, W = int(rng.randint(1, 3)), int(rng.randint(5, 9)), \
        int(rng.randint(5, 9))
    Ci, Co = int(rng.randint(1, 5)), int(rng.randint(1, 5))
    x = rng.randn(B, H, W, Ci).astype(np.float32) * 0.5
    w = rng.randn(k, k, Ci, Co).astype(np.float32) * 0.5
    got = np.asarray(OP_TABLE["conv2d"](x, w, stride=(stride, stride),
                                        padding=padding,
                                        dilation=(dil, dil)))
    want = tf.nn.conv2d(x.astype(np.float64), w.astype(np.float64),
                        strides=(1, stride, stride, 1), padding=padding,
                        dilations=(1, dil, dil, 1)).numpy()
    return got, want, dict(op="conv2d", k=k, stride=stride, dil=dil,
                           padding=padding, shape=(B, H, W, Ci, Co))


def _conv2d_nchw_case(rng):
    import torch
    import torch.nn.functional as TF_
    k = int(rng.randint(1, 4))
    stride = int(rng.randint(1, 3))
    dil = int(rng.randint(1, 3))
    pads = tuple(int(p) for p in rng.randint(0, 3, 4))   # t, l, b, r
    B, H, W = int(rng.randint(1, 3)), int(rng.randint(5, 9)), \
        int(rng.randint(5, 9))
    Ci, Co = int(rng.randint(1, 5)), int(rng.randint(1, 5))
    eff = dil * (k - 1) + 1
    if H + pads[0] + pads[2] < eff or W + pads[1] + pads[3] < eff:
        pads = (eff, eff, eff, eff)                      # keep it valid
    x = rng.randn(B, Ci, H, W).astype(np.float32) * 0.5
    w = rng.randn(Co, Ci, k, k).astype(np.float32) * 0.5
    got = np.asarray(OP_TABLE["conv2d_nchw"](
        x, w, stride=(stride, stride), pads=pads, dilation=(dil, dil)))
    xp = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]),
                    (pads[1], pads[3])))
    want = TF_.conv2d(torch.from_numpy(xp).double(),
                      torch.from_numpy(w).double(), None,
                      stride=stride, padding=0, dilation=dil).numpy()
    return got, want, dict(op="conv2d_nchw", k=k, stride=stride, dil=dil,
                           pads=pads, shape=(B, Ci, H, W, Co))


def _deconv2d_nchw_case(rng):
    import torch
    import torch.nn.functional as TF_
    k = int(rng.randint(2, 4))
    stride = int(rng.randint(1, 3))
    dil = int(rng.randint(1, 3))
    p = int(rng.randint(0, min(k, 2)))                   # symmetric
    outp = int(rng.randint(0, stride))
    B, H, W = 1, int(rng.randint(3, 6)), int(rng.randint(3, 6))
    Ci, Co = int(rng.randint(1, 4)), int(rng.randint(1, 4))
    if dil * (k - 1) - p < 0:
        p = 0
    x = rng.randn(B, Ci, H, W).astype(np.float32) * 0.5
    w = rng.randn(Ci, Co, k, k).astype(np.float32) * 0.5
    got = np.asarray(OP_TABLE["deconv2d_nchw"](
        x, w, stride=(stride, stride), pads=(p, p, p, p),
        dilation=(dil, dil), output_padding=(outp, outp)))
    want = TF_.conv_transpose2d(
        torch.from_numpy(x).double(), torch.from_numpy(w).double(),
        None, stride=stride, padding=p, output_padding=outp,
        dilation=dil).numpy()
    return got, want, dict(op="deconv2d_nchw", k=k, stride=stride,
                           dil=dil, p=p, outp=outp,
                           shape=(B, Ci, H, W, Co))


def _pool2d_case(rng):
    k = int(rng.randint(2, 4))
    stride = int(rng.randint(1, 3))
    padding = ["SAME", "VALID"][rng.randint(2)]
    mode = ["max", "avg"][rng.randint(2)]
    B, H, W, C = (int(rng.randint(1, 3)), int(rng.randint(5, 9)),
                  int(rng.randint(5, 9)), int(rng.randint(1, 4)))
    x = rng.randn(B, H, W, C).astype(np.float32)
    op = OP_TABLE["max_pooling2d" if mode == "max" else "avg_pooling2d"]
    got = np.asarray(op(x, kernel=(k, k), stride=(stride, stride),
                        padding=padding))
    fn = tf.nn.max_pool2d if mode == "max" else tf.nn.avg_pool2d
    want = fn(x.astype(np.float64), k, (1, stride, stride, 1),
              padding).numpy()
    return got, want, dict(op=f"{mode}_pool", k=k, stride=stride,
                           padding=padding, shape=(B, H, W, C))


SAMPLERS = [_conv2d_nhwc_case, _conv2d_nchw_case, _deconv2d_nchw_case,
            _pool2d_case]


@pytest.mark.parametrize("seed", range(N_CASES))
def test_conv_config_fuzz(seed):
    rng = np.random.RandomState(7000 + seed)
    sampler = SAMPLERS[seed % len(SAMPLERS)]
    got, want, cfg = sampler(rng)
    assert got.shape == want.shape, (cfg, got.shape, want.shape)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4,
                               err_msg=str(cfg))
