"""Fused-kernel tier (ops/pallas): conformance, dispatch, tile autotuning.

The tier's contract is two implementations per kernel — Pallas (TileConfig-
parameterized) and a pure-jnp reference that is the definition of
correctness — behind one dispatch layer.  These tests pin:

- conformance: `pallas(interpret=True) == reference` across dtypes
  (f32/bf16/int8), causal/masked attention variants, and ragged
  non-multiple-of-tile shapes (masked tails / zero padding).  The int8
  contraction + scale epilogue is pinned *bitwise* (integer accumulation
  is exact and the f32 dequant epilogue is shared code); bias-fused
  variants allow 1-ulp-scale drift because XLA may contract the
  `y*scale + b` epilogue into an FMA inside the kernel.
- dispatch: CPU always gets the reference in auto mode; forced `pallas`
  mode runs interpret-mode kernels on CPU; a missing
  `jax.experimental.pallas` degrades to reference-only instead of
  breaking; decisions are counted in `ops_kernel_dispatch_total`.
- tiles: TileAutotuner grid+greedy search, memoization, persistence via
  the per-device tile table, zero re-search on replay (cache-hit metric),
  and `kernel_tier_fingerprint` splitting AOT keys on mode/tile changes.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.compile.autotune import (TileAutotuner,
                                                 autotune_tiles,
                                                 load_tile_table,
                                                 save_tile_entry,
                                                 tile_table_path)
from deeplearning4j_tpu.compile.fingerprint import (kernel_tier_fingerprint,
                                                    model_fingerprint)
from deeplearning4j_tpu.monitor.instrument import ops_instruments
from deeplearning4j_tpu.ops import pallas as tier
from deeplearning4j_tpu.ops.pallas import attention as pa
from deeplearning4j_tpu.ops.pallas import matmul as pm
from deeplearning4j_tpu.ops.pallas.tiles import TileConfig, shape_class
from deeplearning4j_tpu.ops.quant_kernels import (dequant_epilogue,
                                                  quantize_tensor,
                                                  quantized_dense,
                                                  quantized_matmul,
                                                  quantized_matmul_static)

dispatch = tier.dispatch


@pytest.fixture(autouse=True)
def _reset_dispatch():
    yield
    dispatch.reset()


def _rng(seed=0):
    return np.random.RandomState(seed)


def _qkv(rng, B, H, T, S, D, dtype=np.float32):
    return (jnp.asarray(rng.randn(B, H, T, D).astype(dtype) * 0.3),
            jnp.asarray(rng.randn(B, H, S, D).astype(dtype) * 0.3),
            jnp.asarray(rng.randn(B, H, S, D).astype(dtype) * 0.3))


SMALL_ATT = TileConfig(block_q=32, block_kv=64)
SMALL_MM = TileConfig(block_m=8, block_n=128, block_k=128)


# ---------------------------------------------------------------------------
# conformance: attention
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal,masked", [(False, False), (True, False),
                                           (False, True), (True, True)])
def test_attention_conformance_variants(causal, masked):
    rng = _rng(1)
    q, k, v = _qkv(rng, 2, 2, 128, 128, 64)
    mask = (jnp.asarray((rng.rand(2, 128) > 0.2).astype(np.float32))
            if masked else None)
    out = pa.flash_attention(q, k, v, mask=mask, causal=causal,
                             tile=SMALL_ATT, interpret=True)
    ref = pa.attention_reference(q, k, v, mask=mask, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


def test_attention_conformance_ragged_masked_tail():
    """T=100/S=72 hit no block multiple: the wrapper zero-pads and knocks
    the padded KV out through the additive mask, then slices Q rows."""
    rng = _rng(2)
    for causal in (False, True):
        q, k, v = _qkv(rng, 2, 2, 100, 72, 64)
        keep = (rng.rand(2, 72) > 0.3).astype(np.float32)
        keep[:, 0] = 1.0   # no fully-masked rows: those are undefined
        mask = jnp.asarray(keep)
        out = pa.flash_attention(q, k, v, mask=mask, causal=causal,
                                 tile=SMALL_ATT, interpret=True)
        ref = pa.attention_reference(q, k, v, mask=mask, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-6, atol=2e-6)


def test_attention_conformance_bf16():
    rng = _rng(3)
    q, k, v = _qkv(rng, 1, 2, 128, 128, 64)
    q, k, v = (a.astype(jnp.bfloat16) for a in (q, k, v))
    out = pa.flash_attention(q, k, v, tile=SMALL_ATT, interpret=True)
    ref = pa.attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_attention_grad_through_ragged_pallas():
    rng = _rng(4)
    q, k, v = _qkv(rng, 1, 1, 100, 72, 64)

    def f(fn):
        return jax.grad(lambda q_: fn(q_).sum())(q)

    g_pal = f(lambda q_: pa.flash_attention(q_, k, v, tile=SMALL_ATT,
                                            interpret=True))
    g_ref = f(lambda q_: pa.attention_reference(q_, k, v))
    np.testing.assert_allclose(np.asarray(g_pal), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# conformance: matmul family
# ---------------------------------------------------------------------------

def _int8_case(rng, M=37, K=70, N=45):
    xq = jnp.asarray(rng.randint(-127, 128, (M, K)).astype(np.int8))
    wq = jnp.asarray(rng.randint(-127, 128, (K, N)).astype(np.int8))
    ws = jnp.asarray(rng.rand(N).astype(np.float32) * 0.1)
    return xq, wq, ws


def test_int8_matmul_bitwise_ragged():
    """The headline tier guarantee: int8×int8→int32 stays exact under any
    tiling and the shared f32 dequant epilogue makes the scale application
    bit-identical to the reference — even on ragged M/K/N."""
    rng = _rng(5)
    for (M, K, N) in [(37, 70, 45), (8, 128, 128), (130, 257, 129)]:
        xq, wq, ws = _int8_case(rng, M, K, N)
        got = pm.int8_matmul(xq, wq, ws, x_scale=jnp.float32(0.02),
                             tile=SMALL_MM, interpret=True)
        want = pm.int8_matmul_reference(xq, wq, ws,
                                        x_scale=jnp.float32(0.02))
        assert got.dtype == want.dtype
        assert bool(jnp.all(got == want)), (M, K, N)


def test_int8_matmul_bias_epilogue():
    rng = _rng(6)
    xq, wq, ws = _int8_case(rng)
    bias = jnp.asarray(rng.randn(45).astype(np.float32))
    got = pm.int8_matmul(xq, wq, ws, x_scale=jnp.float32(0.02), bias=bias,
                         tile=SMALL_MM, interpret=True)
    want = pm.int8_matmul_reference(xq, wq, ws, x_scale=jnp.float32(0.02),
                                    bias=bias)
    # fused bias add may FMA-contract inside the kernel: 1-ulp tolerance
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-4)


def test_q_matmul_weight_only_conformance():
    rng = _rng(7)
    _, wq, ws = _int8_case(rng, K=70, N=45)
    for dt, tol in ((np.float32, 1e-4), (jnp.bfloat16, 5e-2)):
        x = jnp.asarray(rng.randn(33, 70).astype(np.float32)).astype(dt)
        got = pm.q_matmul(x, wq, ws, tile=SMALL_MM, interpret=True)
        want = pm.q_matmul_reference(x, wq, ws)
        assert got.dtype == want.dtype
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=tol, atol=tol)


@pytest.mark.parametrize("act", ["identity", "relu", "tanh", "sigmoid",
                                 "gelu"])
def test_fused_dense_activation_epilogues(act):
    rng = _rng(8)
    x = jnp.asarray(rng.randn(33, 70).astype(np.float32))
    w = jnp.asarray(rng.randn(70, 45).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(45).astype(np.float32))
    got = pm.fused_dense(x, w, bias=b, activation=act, tile=SMALL_MM,
                         interpret=True)
    want = pm.fused_dense_reference(x, w, bias=b, activation=act)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fused_dense_grads_match_reference():
    rng = _rng(9)
    x = jnp.asarray(rng.randn(16, 24).astype(np.float32))
    w = jnp.asarray(rng.randn(24, 40).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(40).astype(np.float32))

    def loss(fn, *args):
        return jax.grad(lambda t: fn(*t).sum())(args)

    g_pal = loss(lambda x_, w_, b_: pm.fused_dense(
        x_, w_, b_, activation="tanh", tile=SMALL_MM, interpret=True),
        x, w, b)
    g_ref = loss(lambda x_, w_, b_: pm.fused_dense_reference(
        x_, w_, b_, activation="tanh"), x, w, b)
    for gp, gr in zip(g_pal, g_ref):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                   rtol=1e-5, atol=1e-6)


def test_quantized_matmul_static_bitwise_across_modes():
    """The quant satellite: `quantized_matmul_static` keeps the int32
    contraction end-to-end and shares `dequant_epilogue`, so forcing the
    tier to Pallas changes nothing — bit-for-bit."""
    rng = _rng(10)
    x = jnp.asarray(rng.randn(16, 32).astype(np.float32))
    qt = quantize_tensor(rng.randn(32, 24).astype(np.float32))
    ref = quantized_matmul_static(x, qt, 0.05)
    dispatch.set_dispatch_mode("pallas")
    pal = quantized_matmul_static(x, qt, 0.05)
    assert bool(jnp.all(ref == pal))


def test_dequant_epilogue_shared_math():
    rng = _rng(11)
    y = jnp.asarray(rng.randint(-1000, 1000, (7, 5)).astype(np.int32))
    scale = jnp.asarray(rng.rand(1, 5).astype(np.float32))
    out = dequant_epilogue(y, scale, out_dtype=jnp.float32)
    want = (np.asarray(y).astype(np.float32)
            * np.asarray(scale).astype(np.float32))
    assert out.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(out), want)


def test_quantized_paths_forced_pallas_match_reference():
    rng = _rng(12)
    x = jnp.asarray(rng.randn(16, 32).astype(np.float32))
    qt = quantize_tensor(rng.randn(32, 24).astype(np.float32))
    b = jnp.asarray(rng.randn(24).astype(np.float32))
    ref_m = quantized_matmul(x, qt)
    ref_d = quantized_dense(x, qt, b)
    dispatch.set_dispatch_mode("pallas")
    np.testing.assert_allclose(np.asarray(quantized_matmul(x, qt)),
                               np.asarray(ref_m), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(quantized_dense(x, qt, b)),
                               np.asarray(ref_d), rtol=1e-5, atol=1e-5)


def test_quantized_mha_forced_pallas_matches_reference():
    """quantized_mha's projections + attention all route through the tier
    under forced mode (docs/quantization.md cross-link)."""
    rng = _rng(13)
    B, T, F, H = 2, 16, 32, 2
    x = jnp.asarray(rng.randn(B, T, F).astype(np.float32) * 0.3)
    w_qkv = quantize_tensor(rng.randn(F, 3 * 128).astype(np.float32) * 0.1)
    w_out = quantize_tensor(rng.randn(128, F).astype(np.float32) * 0.1)
    from deeplearning4j_tpu.ops.attention_kernels import quantized_mha
    ref = quantized_mha(x, w_qkv, w_out, n_heads=H)
    dispatch.set_dispatch_mode("pallas")
    pal = quantized_mha(x, w_qkv, w_out, n_heads=H)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# dispatch layer
# ---------------------------------------------------------------------------

def test_dispatch_cpu_auto_always_reference():
    rng = _rng(14)
    q = jnp.asarray(rng.randn(1, 1, 4096, 64).astype(np.float32))
    xq, wq, ws = _int8_case(rng, 512, 512, 512)
    x = jnp.asarray(rng.randn(512, 512).astype(np.float32))
    assert dispatch.dispatch_mode() == "auto"
    assert dispatch.resolve("attention", q, q, q) == "reference"
    assert dispatch.resolve("int8_matmul", xq, wq, ws,
                            jnp.float32(0.1)) == "reference"
    assert dispatch.resolve("q_matmul", x, wq, ws) == "reference"
    assert dispatch.resolve("fused_dense", x, x) == "reference"


def test_dispatch_forced_reference_mode():
    rng = _rng(15)
    xq, wq, ws = _int8_case(rng)
    dispatch.set_dispatch_mode("reference")
    assert dispatch.resolve("int8_matmul", xq, wq, ws) == "reference"


def test_dispatch_forced_pallas_respects_hard_supports():
    rng = _rng(16)
    dispatch.set_dispatch_mode("pallas")
    xq, wq, ws = _int8_case(rng)
    assert dispatch.resolve("int8_matmul", xq, wq, ws) == "pallas"
    # f64 activations are a hard no for the kernels (x64 test config)
    x64 = jnp.asarray(_rng(0).randn(8, 70).astype(np.float64))
    assert dispatch.resolve("q_matmul", x64, wq, ws) == "reference"
    # 3D mask is a hard no for the flash kernel's [B, S] mask contract
    q = jnp.asarray(_rng(0).randn(1, 1, 64, 64).astype(np.float32))
    bad_mask = jnp.ones((1, 64, 64), jnp.float32)
    assert dispatch.resolve("attention", q, q, q,
                            mask=bad_mask) == "reference"


def test_dispatch_missing_pallas_degrades_to_reference(monkeypatch):
    """CI-hygiene satellite: without jax.experimental.pallas the tier must
    answer `reference` everywhere — even forced — not raise."""
    rng = _rng(17)
    xq, wq, ws = _int8_case(rng)
    monkeypatch.setattr(dispatch, "_pallas_ok", False)
    dispatch.set_dispatch_mode("pallas")
    assert not dispatch.pallas_available()
    assert dispatch.resolve("int8_matmul", xq, wq, ws) == "reference"
    assert kernel_tier_fingerprint()["pallas"] is False


def test_dispatch_decisions_counted():
    rng = _rng(18)
    xq, wq, ws = _int8_case(rng)
    before = ops_instruments().dispatch("int8_matmul", "reference").value
    dispatch.resolve("int8_matmul", xq, wq, ws)
    after = ops_instruments().dispatch("int8_matmul", "reference").value
    assert after == before + 1


def test_fused_attention_routes_reference_on_cpu():
    rng = _rng(19)
    from deeplearning4j_tpu.ops.attention_kernels import (fused_attention,
                                                         mha_reference)
    q, k, v = _qkv(rng, 1, 1, 64, 64, 32)
    np.testing.assert_array_equal(
        np.asarray(fused_attention(q, k, v)),
        np.asarray(mha_reference(q, k, v)))


def test_fused_attention_forced_pallas_interpret_on_cpu():
    rng = _rng(20)
    from deeplearning4j_tpu.ops.attention_kernels import (fused_attention,
                                                         mha_reference)
    q, k, v = _qkv(rng, 1, 1, 64, 64, 64)
    ref = mha_reference(q, k, v, causal=True)
    dispatch.set_dispatch_mode("pallas")
    out = fused_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


def test_dense_layer_routes_tier_on_accelerator(monkeypatch):
    """DenseLayer asks the tier; on a (faked) TPU with profitable shapes
    it must call the fused tile, passing bias + activation through."""
    from deeplearning4j_tpu.nn.core import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer
    rng = _rng(21)
    x = jnp.asarray(rng.randn(256, 128).astype(np.float32))
    layer = DenseLayer(n_out=128, activation="relu")
    params, state, _ = layer.initialize(jax.random.PRNGKey(0),
                                        InputType.feed_forward(128))
    calls = {}

    def fake_fused(x_, w_, bias=None, activation=None, tile=None,
                   interpret=False):
        calls.update(activation=activation, tile=tile, bias=bias)
        return pm.fused_dense_reference(x_, w_, bias=bias,
                                        activation=activation)

    monkeypatch.setattr(pm, "fused_dense", fake_fused)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    y, _ = layer.apply(params, state, x)
    assert calls["activation"] == "relu"
    assert calls["bias"] is params["b"]
    ref = np.maximum(np.asarray(x) @ np.asarray(params["W"])
                     + np.asarray(params["b"]), 0.0)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# tiles + autotuner
# ---------------------------------------------------------------------------

def test_tile_config_roundtrip_and_shape_class():
    cfg = TileConfig(block_q=128, block_kv=256, block_m=64, block_n=512,
                     block_k=1024)
    assert TileConfig.from_json(json.loads(json.dumps(cfg.to_json()))) == cfg
    assert shape_class(m=37, k=70, n=45) == "k128-m64-n64"
    assert shape_class(m=512, k=512, n=512) == shape_class(m=400, k=300,
                                                           n=257)


def test_get_tile_precedence():
    assert dispatch.get_tile("int8_matmul") == \
        tier.DEFAULT_TILES["int8_matmul"]
    wide = TileConfig(block_m=512)
    narrow = TileConfig(block_m=64)
    dispatch.set_tile("int8_matmul", wide)
    assert dispatch.get_tile("int8_matmul", "m64-k128-n128") == wide
    dispatch.set_tile("int8_matmul", narrow, "m64-k128-n128")
    assert dispatch.get_tile("int8_matmul", "m64-k128-n128") == narrow
    assert dispatch.get_tile("int8_matmul", "other") == wide


def test_tile_autotuner_finds_rigged_optimum():
    calls = []

    def measure(cfg):
        calls.append(cfg)
        return -(abs(cfg.block_m - 512) + abs(cfg.block_n - 128)
                 + abs(cfg.block_k - 1024))

    tuner = TileAutotuner(measure, "int8_matmul")
    best = tuner.search()
    assert (best.block_m, best.block_n, best.block_k) == (512, 128, 1024)
    assert tuner.best_rate == 0
    # memoized: every evaluated config measured exactly once
    keys = [c.config_key() for c in calls]
    assert len(keys) == len(set(keys)) == tuner.evaluated


def test_autotune_tiles_persists_then_replays_with_zero_search(tmp_path):
    counts = {"n": 0}

    def measure(cfg):
        counts["n"] += 1
        return float(cfg.block_m)

    hits0 = ops_instruments().tile_cache_hits.value
    t1, info1 = autotune_tiles("int8_matmul", "m512-k512-n512", measure,
                               str(tmp_path))
    assert info1["source"] == "searched" and counts["n"] > 0
    assert t1.block_m == 512
    searched = counts["n"]
    # fresh process simulated: no tuner memo survives, only the table
    t2, info2 = autotune_tiles("int8_matmul", "m512-k512-n512", measure,
                               str(tmp_path))
    assert info2["source"] == "cache"
    assert counts["n"] == searched            # ZERO re-search
    assert t2 == t1
    assert ops_instruments().tile_cache_hits.value == hits0 + 1
    # the winner is installed for dispatch + fingerprinting
    assert dispatch.get_tile("int8_matmul", "m512-k512-n512") == t1
    assert "int8_matmul/m512-k512-n512" in \
        kernel_tier_fingerprint()["tiles"]


def test_tile_table_roundtrip_and_corruption(tmp_path):
    cfg = TileConfig(block_m=64, block_n=128, block_k=256)
    save_tile_entry(str(tmp_path), "fused_dense", "m256-k256-n256", cfg,
                    rate=123.0, device_kind="testchip")
    table = load_tile_table(str(tmp_path), device_kind="testchip")
    assert table == {"fused_dense/m256-k256-n256": cfg}
    # corrupt file → empty table, not an exception
    with open(tile_table_path(str(tmp_path), "testchip"), "w") as f:
        f.write("{not json")
    assert load_tile_table(str(tmp_path), device_kind="testchip") == {}


def test_kernel_tier_fingerprint_splits_aot_keys():
    """reference, Pallas-default, and autotuned-tile programs must never
    share an AOT cache entry (acceptance criterion)."""

    class M:
        pass

    m = M()
    fps = set()
    fps.add(model_fingerprint(m))
    dispatch.set_dispatch_mode("pallas")
    fps.add(model_fingerprint(m))
    dispatch.set_tile("int8_matmul", TileConfig(block_m=512))
    fps.add(model_fingerprint(m))
    dispatch.set_tile("int8_matmul", TileConfig(block_m=128))
    fps.add(model_fingerprint(m))
    assert len(fps) == 4
