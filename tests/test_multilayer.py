"""MultiLayerNetwork end-to-end tests (reference: deeplearning4j-core
integration tests — convergence, serde round-trip, exact resume)."""
import os
import tempfile

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import ArrayDataSetIterator, AsyncDataSetIterator
from deeplearning4j_tpu.nn import (
    BatchNormalizationLayer, ConvolutionLayer, DenseLayer, InputType,
    MultiLayerConfiguration, MultiLayerNetwork, NeuralNetConfiguration,
    OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.train import Adam, Nesterovs


def two_moons(n=256, seed=0):
    rng = np.random.default_rng(seed)
    t = rng.uniform(0, np.pi, n)
    x0 = np.stack([np.cos(t), np.sin(t)], -1) + rng.normal(0, 0.1, (n, 2))
    x1 = np.stack([1 - np.cos(t), 0.5 - np.sin(t)], -1) + rng.normal(0, 0.1, (n, 2))
    x = np.concatenate([x0, x1]).astype(np.float32)
    y = np.zeros((2 * n, 2), np.float32)
    y[:n, 0] = 1
    y[n:, 1] = 1
    idx = rng.permutation(2 * n)
    return x[idx], y[idx]


def mlp_conf(updater=None, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Adam(1e-2))
            .weight_init("XAVIER")
            .list([
                DenseLayer(n_out=32, activation="relu"),
                DenseLayer(n_out=32, activation="relu"),
                OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
            ])
            .set_input_type(InputType.feed_forward(2))
            .build())


def test_mlp_converges():
    x, y = two_moons()
    net = MultiLayerNetwork(mlp_conf()).init()
    it = ArrayDataSetIterator(x, y, batch_size=64, shuffle=True, seed=0)
    first = net.score_for(x, y)
    net.fit(it, epochs=30)
    last = net.score_for(x, y)
    assert last < first * 0.3, (first, last)
    preds = np.asarray(net.output(x))
    acc = (preds.argmax(-1) == y.argmax(-1)).mean()
    assert acc > 0.95, acc


def test_output_is_probability():
    x, y = two_moons(32)
    net = MultiLayerNetwork(mlp_conf()).init()
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_small_cnn_trains():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8, 8, 1)).astype(np.float32)
    y = np.zeros((64, 3), np.float32)
    # label depends on mean sign / magnitude: learnable
    m = x.mean((1, 2, 3))
    y[np.arange(64), np.digitize(m, [-0.05, 0.05])] = 1
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(5e-3)).weight_init("RELU")
            .list([
                ConvolutionLayer(n_out=4, kernel_size=3, activation="relu"),
                SubsamplingLayer(kernel_size=2, stride=2),
                BatchNormalizationLayer(),
                DenseLayer(n_out=16, activation="relu"),
                OutputLayer(n_out=3, loss="mcxent", activation="softmax"),
            ])
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    first = net.score_for(x, y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score_for(x, y) < first


def test_json_roundtrip():
    conf = mlp_conf(updater=Nesterovs(0.05, momentum=0.9))
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    assert len(conf2.layers) == 3
    assert conf2.layers[0].n_out == 32
    net = MultiLayerNetwork(conf2).init()
    x, y = two_moons(16)
    net.fit(x, y)  # builds and runs


def test_flat_params_roundtrip():
    net = MultiLayerNetwork(mlp_conf()).init()
    flat = net.params()
    assert flat.size == net.num_params()
    x, _ = two_moons(8)
    before = np.asarray(net.output(x))
    flat2 = flat * 2.0
    net.set_params(flat2)
    after = np.asarray(net.output(x))
    assert not np.allclose(before, after)
    net.set_params(flat)
    np.testing.assert_allclose(np.asarray(net.output(x)), before, rtol=1e-6)


def test_save_load_exact_resume():
    """Checkpoint must restore training exactly (reference: ModelSerializer
    + updater state, SURVEY.md §5.4)."""
    x, y = two_moons(64, seed=3)
    net = MultiLayerNetwork(mlp_conf()).init()
    for _ in range(5):
        net.fit(x, y)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.zip")
        net.save(path)
        net2 = MultiLayerNetwork.load(path)
        assert net2.iteration == net.iteration
        np.testing.assert_allclose(net2.params(), net.params(), rtol=1e-7)
        # identical further training trajectory (same rng seed state caveat:
        # both nets continue from the same param/updater state with no
        # stochastic layers -> identical updates)
        net._rng = net2._rng  # align dropout streams (none here)
        net.fit(x, y)
        net2.fit(x, y)
        np.testing.assert_allclose(net2.params(), net.params(), rtol=1e-6)


def test_async_iterator_equivalence():
    x, y = two_moons(64)
    base = ArrayDataSetIterator(x, y, batch_size=16)
    async_it = AsyncDataSetIterator(base, queue_size=2)
    batches = [ds for ds in async_it]
    assert len(batches) == len(x) // 16
    np.testing.assert_allclose(
        np.concatenate([b.features for b in batches]), x)


def test_evaluation():
    x, y = two_moons(128)
    net = MultiLayerNetwork(mlp_conf()).init()
    it = ArrayDataSetIterator(x, y, batch_size=32, shuffle=True, seed=1)
    net.fit(it, epochs=30)
    ev = net.evaluate(ArrayDataSetIterator(x, y, batch_size=64))
    assert ev.accuracy() > 0.95
    assert 0.0 < ev.f1() <= 1.0
    assert "Accuracy" in ev.stats()


def test_frozen_layer_does_not_update():
    conf = mlp_conf()
    conf.layers[0].frozen = True
    net = MultiLayerNetwork(conf).init()
    x, y = two_moons(32)
    w_before = np.asarray(net.params_["layer_0"]["W"]).copy()
    net.fit(x, y)
    np.testing.assert_allclose(np.asarray(net.params_["layer_0"]["W"]), w_before)
    assert not np.allclose(np.asarray(net.params_["layer_1"]["W"]),
                           w_before[:32, :32] if w_before.shape[0] >= 32 else 0)


def test_gradient_checkpointing_matches_plain():
    """remat (jax.checkpoint per layer) must be numerically identical to the
    plain path — it only changes what the backward rematerializes."""
    rng = np.random.RandomState(11)
    x = rng.randn(8, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]

    from deeplearning4j_tpu.train.updaters import Sgd

    def build(remat):
        b = (NeuralNetConfiguration.builder().seed(5)
             .updater(Sgd(0.1)))
        if remat:
            b = b.gradient_checkpointing()
        conf = (b.list([DenseLayer(n_out=16, activation="tanh"),
                        DenseLayer(n_out=8, activation="relu"),
                        OutputLayer(n_out=3, loss="mcxent",
                                    activation="softmax")])
                .set_input_type(InputType.feed_forward(6)).build())
        return MultiLayerNetwork(conf).init()

    a, b_ = build(False), build(True)
    assert b_.conf.remat and not a.conf.remat
    # same seed -> same init; train both 5 steps; params must bit-match
    for _ in range(5):
        a.fit(x, y)
        b_.fit(x, y)
    np.testing.assert_allclose(np.asarray(a.params()),
                               np.asarray(b_.params()), atol=1e-6)
    # config round-trips the flag
    from deeplearning4j_tpu.nn import MultiLayerConfiguration
    assert MultiLayerConfiguration.from_json(b_.conf.to_json()).remat


def test_fit_steps_matches_sequential_fit():
    """fit_steps (one lax.scan dispatch over k steps) must be bit-equal to
    k sequential fit() calls — same updater math, rng chain, counters."""
    rng = np.random.RandomState(3)
    xs = rng.rand(5, 8, 2).astype(np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.randint(0, 2, (5, 8))]

    a = MultiLayerNetwork(mlp_conf()).init()
    b = MultiLayerNetwork(mlp_conf()).init()
    for i in range(5):
        a.fit(xs[i], ys[i])
    losses = b.fit_steps(xs, ys)
    assert losses.shape == (5,)
    np.testing.assert_allclose(np.asarray(a.params()),
                               np.asarray(b.params()), atol=0)
    assert a.iteration == b.iteration == 5
    assert abs(a.score() - b.score()) < 1e-7
    # mixing modes keeps the counter chain intact
    b.fit(xs[0], ys[0])
    assert b.iteration == 6


def test_fit_iterator_fused_steps_matches_sequential():
    """fit(iterator, fused_steps=4) == fit(iterator): blocks of 4 go
    through one scan dispatch, the odd tail through the per-step path."""
    x, y = two_moons(n=72)          # 144 samples -> 9 batches of 16
    a = MultiLayerNetwork(mlp_conf()).init()
    b = MultiLayerNetwork(mlp_conf()).init()
    ita = ArrayDataSetIterator(x, y, batch_size=16)
    itb = ArrayDataSetIterator(x, y, batch_size=16)
    a.fit(ita, epochs=2)
    b.fit(itb, epochs=2, fused_steps=4)
    np.testing.assert_allclose(np.asarray(a.params()),
                               np.asarray(b.params()), atol=0)
    assert a.iteration == b.iteration == 18


def test_fit_async_iterator_with_fused_steps():
    """The canonical hot loop (SURVEY §3.1): async host prefetch feeding
    the fused k-step dispatch — must equal plain sequential training."""
    x, y = two_moons(n=64)          # 128 samples -> 8 batches of 16
    a = MultiLayerNetwork(mlp_conf()).init()
    b = MultiLayerNetwork(mlp_conf()).init()
    a.fit(ArrayDataSetIterator(x, y, batch_size=16), epochs=2)
    b.fit(AsyncDataSetIterator(ArrayDataSetIterator(x, y, batch_size=16),
                               queue_size=3),
          epochs=2, fused_steps=4)
    np.testing.assert_allclose(np.asarray(a.params()),
                               np.asarray(b.params()), atol=0)
    assert a.iteration == b.iteration == 16
