"""Zoo model construction + forward-shape + tiny-train smoke tests
(reference `deeplearning4j-zoo` tests `TestInstantiation.java`).

Image models instantiate at reduced input sizes to keep CPU CI fast; the
architectures are size-agnostic (Same-padded convs + global pooling).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (AlexNet, Darknet19, LeNet, ResNet50,
                                    SimpleCNN, SqueezeNet, TextGenLSTM, UNet,
                                    VGG16, VGG19, ZOO_REGISTRY)


def test_registry_contents():
    for name in ["LeNet", "AlexNet", "VGG16", "VGG19", "ResNet50",
                 "SqueezeNet", "Darknet19", "UNet", "SimpleCNN",
                 "TextGenLSTM"]:
        assert name in ZOO_REGISTRY


def test_lenet_trains_mnist_shaped():
    net = LeNet(n_classes=10).init_model()
    rng = np.random.RandomState(0)
    x = rng.rand(16, 28, 28, 1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]
    s0 = net.score_for(x, y)
    for _ in range(10):
        net.fit(x, y)
    assert net.score_for(x, y) < s0
    assert net.output(x).shape == (16, 10)


def test_simplecnn_forward():
    net = SimpleCNN(n_classes=5, input_shape=(32, 32, 3)).init_model()
    x = np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32)
    assert net.output(x).shape == (4, 5)


def test_resnet50_structure_and_forward():
    m = ResNet50(n_classes=11, input_shape=(64, 64, 3))
    conf = m.conf()
    # 16 bottleneck blocks -> 16 add vertices
    adds = [n for n in conf.vertices if n.endswith("_add")]
    assert len(adds) == 16
    net = m.init_model()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 11)
    assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-4)


def test_resnet50_trains():
    net = ResNet50(n_classes=3, input_shape=(32, 32, 3)).init_model()
    rng = np.random.RandomState(0)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    s0 = net.score_for(x, y)
    for _ in range(8):
        net.fit(x, y)
    assert net.score_for(x, y) < s0


def test_squeezenet_forward():
    net = SqueezeNet(n_classes=7, input_shape=(64, 64, 3)).init_model()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 7)


def test_unet_forward_shape():
    net = UNet(input_shape=(64, 64, 3), base_filters=8).init_model()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 64, 64, 1)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) <= 1).all()


def test_textgen_lstm_trains():
    m = TextGenLSTM(n_classes=20, input_shape=(16, 20), lstm_units=32)
    net = m.init_model()
    rng = np.random.RandomState(0)
    idx = rng.randint(0, 20, (8, 16))
    x = np.eye(20, dtype=np.float32)[idx]
    y = np.eye(20, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    s0 = net.score_for(x, y)
    for _ in range(5):
        net.fit(x, y)
    assert net.score_for(x, y) < s0
    assert net.output(x).shape == (8, 16, 20)


@pytest.mark.parametrize("cls", [AlexNet, VGG16, VGG19, Darknet19])
def test_imagenet_models_construct(cls):
    # full 224x224 construct-only (init touches every shape-inference path)
    net = cls(n_classes=10).init_model()
    assert net.num_params() > 1_000_000
