"""Zoo model construction + forward-shape + tiny-train smoke tests
(reference `deeplearning4j-zoo` tests `TestInstantiation.java`).

Image models instantiate at reduced input sizes to keep CPU CI fast; the
architectures are size-agnostic (Same-padded convs + global pooling).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (AlexNet, Darknet19, LeNet, ResNet50,
                                    SimpleCNN, SqueezeNet, TextGenLSTM, UNet,
                                    VGG16, VGG19, ZOO_REGISTRY)


def test_registry_contents():
    for name in ["LeNet", "AlexNet", "VGG16", "VGG19", "ResNet50",
                 "SqueezeNet", "Darknet19", "UNet", "SimpleCNN",
                 "TextGenLSTM"]:
        assert name in ZOO_REGISTRY


def test_lenet_trains_mnist_shaped():
    net = LeNet(n_classes=10).init_model()
    rng = np.random.RandomState(0)
    x = rng.rand(16, 28, 28, 1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]
    s0 = net.score_for(x, y)
    for _ in range(10):
        net.fit(x, y)
    assert net.score_for(x, y) < s0
    assert net.output(x).shape == (16, 10)


def test_simplecnn_forward():
    net = SimpleCNN(n_classes=5, input_shape=(32, 32, 3)).init_model()
    x = np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32)
    assert net.output(x).shape == (4, 5)


def test_resnet50_structure_and_forward():
    m = ResNet50(n_classes=11, input_shape=(64, 64, 3))
    conf = m.conf()
    # 16 bottleneck blocks -> 16 add vertices
    adds = [n for n in conf.vertices if n.endswith("_add")]
    assert len(adds) == 16
    net = m.init_model()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 11)
    assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-4)


def test_resnet50_trains():
    net = ResNet50(n_classes=3, input_shape=(32, 32, 3)).init_model()
    rng = np.random.RandomState(0)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    s0 = net.score_for(x, y)
    for _ in range(8):
        net.fit(x, y)
    assert net.score_for(x, y) < s0


def test_squeezenet_forward():
    net = SqueezeNet(n_classes=7, input_shape=(64, 64, 3)).init_model()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 7)


def test_unet_forward_shape():
    net = UNet(input_shape=(64, 64, 3), base_filters=8).init_model()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 64, 64, 1)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) <= 1).all()


def test_textgen_lstm_trains():
    m = TextGenLSTM(n_classes=20, input_shape=(16, 20), lstm_units=32)
    net = m.init_model()
    rng = np.random.RandomState(0)
    idx = rng.randint(0, 20, (8, 16))
    x = np.eye(20, dtype=np.float32)[idx]
    y = np.eye(20, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    s0 = net.score_for(x, y)
    for _ in range(5):
        net.fit(x, y)
    assert net.score_for(x, y) < s0
    assert net.output(x).shape == (8, 16, 20)


@pytest.mark.parametrize("cls", [AlexNet, VGG16, VGG19, Darknet19])
def test_imagenet_models_construct(cls):
    # full 224x224 construct-only (init touches every shape-inference path)
    net = cls(n_classes=10).init_model()
    assert net.num_params() > 1_000_000


# ---------------------------------------------------------------------------
# VERDICT #8 zoo breadth: Xception, InceptionResNetV1, TinyYOLO, YOLO2
# ---------------------------------------------------------------------------

def test_xception_forward():
    from deeplearning4j_tpu.zoo import Xception
    m = Xception(n_classes=7, input_shape=(64, 64, 3), middle_flow_blocks=1)
    net = m.init_model()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 7)
    assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-4)


def test_inception_resnet_v1_forward_and_blocks():
    from deeplearning4j_tpu.zoo import InceptionResNetV1
    m = InceptionResNetV1(n_classes=6, input_shape=(96, 96, 3),
                          blocks_a=1, blocks_b=1, blocks_c=1,
                          embedding_size=32)
    conf = m.conf()
    assert "a0_scale" in conf.vertices and "c0_scale" in conf.vertices
    net = m.init_model()
    x = np.random.RandomState(1).rand(2, 96, 96, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 6)


def _yolo_labels(rng, B, H, W, A, C):
    """Rasterized label tensor with one assigned box per image."""
    lab = np.zeros((B, H, W, A, 5 + C), np.float32)
    for b in range(B):
        y, x, a = rng.randint(0, H), rng.randint(0, W), rng.randint(0, A)
        lab[b, y, x, a, 0:2] = rng.rand(2)          # tx, ty
        lab[b, y, x, a, 2:4] = rng.randn(2) * 0.1   # tw, th (log space)
        lab[b, y, x, a, 4] = 1.0
        lab[b, y, x, a, 5 + rng.randint(0, C)] = 1.0
    return lab


def test_tiny_yolo_trains_and_decodes():
    from deeplearning4j_tpu.zoo import TinyYOLO
    from deeplearning4j_tpu.nn import YoloUtils
    m = TinyYOLO(n_classes=3, input_shape=(64, 64, 3))
    net = m.init_model()
    rng = np.random.RandomState(0)
    x = rng.rand(2, 64, 64, 3).astype(np.float32)
    A = len(m.anchors)
    # backbone downsamples /32 -> 2x2 grid
    (head,) = net.output(x)
    assert head.shape == (2, 2, 2, A * (5 + 3))
    lab = _yolo_labels(rng, 2, 2, 2, A, 3)
    s0 = None
    for i in range(8):
        net.fit([x], [lab])
        if s0 is None:
            s0 = net.score()
    assert net.score() < s0
    dets = YoloUtils.get_predicted_objects(head, m.anchors, 3,
                                           conf_threshold=0.0)
    assert len(dets) == 2 and all(len(d) >= 1 for d in dets)


def test_yolo2_structure_and_loss():
    from deeplearning4j_tpu.zoo import YOLO2
    m = YOLO2(n_classes=4, input_shape=(64, 64, 3))
    conf = m.conf()
    assert "pt_reorg" in conf.vertices and "merge" in conf.vertices
    net = m.init_model()
    rng = np.random.RandomState(2)
    x = rng.rand(1, 64, 64, 3).astype(np.float32)
    A = len(m.anchors)
    (head,) = net.output(x)
    assert head.shape == (1, 2, 2, A * (5 + 4))
    lab = _yolo_labels(rng, 1, 2, 2, A, 4)
    net.fit([x], [lab])
    assert np.isfinite(net.score())


def test_space_to_depth_layer():
    from deeplearning4j_tpu.nn import SpaceToDepthLayer
    import jax.numpy as jnp
    layer = SpaceToDepthLayer(block_size=2)
    x = np.arange(2 * 4 * 4 * 3, dtype=np.float32).reshape(2, 4, 4, 3)
    out, _ = layer.apply({}, {}, jnp.asarray(x))
    assert out.shape == (2, 2, 2, 12)
    # first output pixel packs the 2x2 spatial block of channel-major cells
    np.testing.assert_array_equal(np.asarray(out)[0, 0, 0, :3], x[0, 0, 0])
    np.testing.assert_array_equal(np.asarray(out)[0, 0, 0, 3:6], x[0, 0, 1])


def test_nasnet_forward_and_structure():
    from deeplearning4j_tpu.zoo import NASNet
    m = NASNet(n_classes=5, input_shape=(32, 32, 3), cells_per_stack=1,
               filters=12, stem_filters=8)
    conf = m.conf()
    # 3 normal cells + 2 reduction cells
    assert "c0_out" in conf.vertices and "c2_out" in conf.vertices
    assert "red1_out" in conf.vertices and "red2_out" in conf.vertices
    net = m.init_model()
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    (out,) = net.output(x)
    assert out.shape == (2, 5)
    assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-4)
    y = np.eye(5, dtype=np.float32)[[0, 3]]
    net.fit([x], [y])
    assert np.isfinite(net.score())
