"""SameDiff-equivalent engine tests (reference: `SameDiffTests.java`,
`OpValidation` framework — forward value, gradient-vs-finite-difference,
serialization round-trip)."""
import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.autodiff.ops import OP_TABLE
from deeplearning4j_tpu.train.updaters import Adam, Sgd


def _mlp_sd(seed_vars=True):
    sd = SameDiff.create()
    x = sd.placeholder("input", shape=(-1, 4))
    y = sd.placeholder("label", shape=(-1, 3))
    w0 = sd.var("w0", "XAVIER", 4, 16)
    b0 = sd.var("b0", np.zeros(16, np.float32))
    w1 = sd.var("w1", "XAVIER", 16, 3)
    b1 = sd.var("b1", np.zeros(3, np.float32))
    h = sd.nn.tanh(sd.nn.linear(x, w0, b0))
    logits = sd.nn.linear(h, w1, b1, name="logits")
    sd.nn.softmax(logits, name="out")
    sd.loss.softmax_cross_entropy(y, logits, name="loss")
    sd.set_loss_variables("loss")
    return sd


def _toy(n=64):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    labels = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
    y = np.eye(3, dtype=np.float32)[labels]
    return x, y


def test_declare_and_output():
    sd = _mlp_sd()
    x, _ = _toy(8)
    out = sd.output({"input": x}, "out")["out"]
    assert out.shape == (8, 3)
    assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-5)


def test_training_converges():
    sd = _mlp_sd()
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-2),
        data_set_feature_mapping=["input"],
        data_set_label_mapping=["label"]))
    x, y = _toy()
    sd.fit(x, y)
    first = sd.score()
    for _ in range(80):
        sd.fit(x, y)
    assert sd.score() < first * 0.5
    pred = np.asarray(sd.output({"input": x}, "out")["out"]).argmax(1)
    truth = y.argmax(1)
    assert (pred == truth).mean() > 0.9


def test_operator_sugar_matches_numpy():
    sd = SameDiff.create()
    a = sd.var("a", np.array([[1., 2.], [3., 4.]], np.float32))
    b = sd.var("b", np.array([[5., 6.], [7., 8.]], np.float32))
    c = (a + b * 2 - 1) / a
    d = (a @ b).rename("mm")
    vals = sd.output({}, c, "mm")
    np.testing.assert_allclose(vals[c.name],
                               (np.array([[1, 2], [3, 4.]])
                                + np.array([[5, 6], [7, 8.]]) * 2 - 1)
                               / np.array([[1, 2], [3, 4.]]), rtol=1e-6)
    np.testing.assert_allclose(vals["mm"],
                               np.array([[1, 2], [3, 4.]])
                               @ np.array([[5, 6], [7, 8.]]), rtol=1e-6)


def test_reductions_and_math_namespace():
    sd = SameDiff.create()
    a = sd.var("a", np.arange(12, dtype=np.float32).reshape(3, 4))
    m = a.mean(axis=0)
    s = sd.math.sum(a, axis=1)
    e = sd.math.exp(sd.constant("z", np.zeros((2,), np.float32)))
    vals = sd.output({}, m, s, e)
    np.testing.assert_allclose(vals[m.name],
                               np.arange(12.).reshape(3, 4).mean(0))
    np.testing.assert_allclose(vals[s.name],
                               np.arange(12.).reshape(3, 4).sum(1))
    np.testing.assert_allclose(vals[e.name], [1.0, 1.0])


def test_conv2d_and_pooling():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(-1, 8, 8, 3))
    w = sd.var("w", "XAVIER", 3, 3, 3, 5)
    c = sd.cnn.conv2d(x, w, padding="SAME", name="conv")
    p = sd.cnn.max_pooling2d(c, name="pool")
    xs = np.random.RandomState(0).rand(2, 8, 8, 3).astype(np.float32)
    vals = sd.output({"x": xs}, "conv", "pool")
    assert vals["conv"].shape == (2, 8, 8, 5)
    assert vals["pool"].shape == (2, 4, 4, 5)


def test_lstm_layer_shapes_and_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(-1, 6, 4))
    y = sd.placeholder("y", shape=(-1, 6, 8))
    w = sd.var("w", "XAVIER", 4, 32)
    rw = sd.var("rw", "XAVIER", 8, 32)
    b = sd.var("b", np.zeros(32, np.float32))
    h = sd.rnn.lstm_layer(x, w, rw, b, name="h")
    sd.loss.mean_squared_error(y, h, name="loss")
    sd.set_loss_variables("loss")
    xs = np.random.RandomState(0).randn(3, 6, 4).astype(np.float32)
    ys = np.random.RandomState(1).randn(3, 6, 8).astype(np.float32)
    out = sd.output({"x": xs}, "h")["h"]
    assert out.shape == (3, 6, 8)
    grads = sd.calculate_gradients({"x": xs, "y": ys}, "w", "rw", "b")
    assert grads["w"].shape == (4, 32)
    assert np.isfinite(grads["w"]).all()
    assert np.abs(grads["rw"]).sum() > 0


def test_gradients_vs_finite_difference():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(-1, 3))
    y = sd.placeholder("y", shape=(-1, 2))
    w = sd.var("w", np.random.RandomState(0).randn(3, 2) * 0.1)  # float64
    logits = sd.nn.linear(x, w, name="logits")
    sd.loss.softmax_cross_entropy(y, logits, name="loss")
    sd.set_loss_variables("loss")
    xs = np.random.RandomState(1).randn(5, 3)
    ys = np.eye(2)[np.random.RandomState(2).randint(0, 2, 5)]
    g = sd.calculate_gradients({"x": xs, "y": ys}, "w")["w"]
    w0 = np.asarray(sd.variables_["w"]).copy()
    eps = 1e-6
    for (i, j) in [(0, 0), (1, 1), (2, 0)]:
        wp = w0.copy(); wp[i, j] += eps
        wm = w0.copy(); wm[i, j] -= eps
        sd.variables_["w"] = jnp.asarray(wp)
        lp = float(sd.output({"x": xs, "y": ys}, "loss")["loss"])
        sd.variables_["w"] = jnp.asarray(wm)
        lm = float(sd.output({"x": xs, "y": ys}, "loss")["loss"])
        fd = (lp - lm) / (2 * eps)
        assert np.isclose(g[i, j], fd, rtol=1e-4, atol=1e-7), (i, j, g[i, j], fd)
    sd.variables_["w"] = jnp.asarray(w0)


def test_save_load_exact_resume(tmp_path):
    sd = _mlp_sd()
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["input"],
        data_set_label_mapping=["label"]))
    x, y = _toy(32)
    for _ in range(5):
        sd.fit(x, y)
    p = str(tmp_path / "sd.zip")
    sd.save(p)
    sd2 = SameDiff.load(p)
    assert sd2.iteration == sd.iteration
    o1 = np.asarray(sd.output({"input": x}, "out")["out"])
    o2 = np.asarray(sd2.output({"input": x}, "out")["out"])
    np.testing.assert_allclose(o1, o2, rtol=1e-6)
    # updater state resumed: next-step scores match
    sd.fit(x, y)
    sd2.fit(x, y)
    assert np.isclose(sd.score(), sd2.score(), rtol=1e-5)


def test_unmapped_op_raises_named_error():
    sd = SameDiff.create()
    a = sd.var("a", np.ones(3, np.float32))
    with pytest.raises(KeyError, match="Unmapped op 'frobnicate'"):
        sd.op("frobnicate", a)


def test_dropout_active_only_in_training():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(-1, 50))
    d = sd.nn.dropout(x, p=0.5, name="d")
    y = sd.placeholder("y", shape=(-1, 50))
    sd.loss.mean_squared_error(y, d, name="loss")
    sd.set_loss_variables("loss")
    xs = np.ones((4, 50), np.float32)
    # inference: identity (no rng fed)
    out = np.asarray(sd.output({"x": xs}, "d")["d"])
    np.testing.assert_array_equal(out, xs)


def test_where_and_comparisons():
    sd = SameDiff.create()
    a = sd.var("a", np.array([-1., 2., -3.], np.float32))
    r = sd.math.where(sd.math.gt(a, 0.0), a, sd.math.zeros_like(a))
    out = sd.output({}, r)[r.name]
    np.testing.assert_allclose(out, [0., 2., 0.])


# ---------------------------------------------------------------------------
# Control flow (reference: Switch/Merge/Enter/Exit/While frames in
# internal/AbstractSession.java → sd.cond / sd.while_loop / sd.scan)
# ---------------------------------------------------------------------------

def test_cond_both_branches():
    sd = SameDiff.create()
    x = sd.placeholder("x", (2, 3))
    p = sd.placeholder("p", ())
    y = sd.cond(p, lambda s, a: s.op("mul", a, 2.0),
                lambda s, a: s.op("neg", a), x)
    xs = np.ones((2, 3), np.float32)
    np.testing.assert_allclose(y.eval({"x": xs, "p": 1.0}), 2 * xs)
    np.testing.assert_allclose(y.eval({"x": xs, "p": 0.0}), -xs)


def test_cond_multi_output_and_gradient():
    sd = SameDiff.create()
    x = sd.placeholder("x", (4,))
    w = sd.var("w", np.array([1., 2., 3., 4.], np.float32))
    p = sd.placeholder("p", ())
    a, b = sd.cond(
        p,
        lambda s, xx, ww: (s.op("mul", xx, ww), s.op("add", xx, ww)),
        lambda s, xx, ww: (s.op("add", xx, ww), s.op("mul", xx, ww)),
        x, w)
    loss = sd.op("sum", a, name="loss")
    sd.set_loss_variables("loss")
    xs = np.full(4, 2.0, np.float32)
    # true branch: d(sum(x*w))/dw = x
    g = sd.calculate_gradients({"x": xs, "p": 1.0}, "w")
    np.testing.assert_allclose(g["w"], xs)
    # false branch: d(sum(x+w))/dw = 1
    g = sd.calculate_gradients({"x": xs, "p": 0.0}, "w")
    np.testing.assert_allclose(g["w"], np.ones(4))


def test_while_loop_accumulates():
    sd = SameDiff.create()
    i0 = sd.placeholder("i0", ())
    acc0 = sd.placeholder("acc0", ())
    _, acc = sd.while_loop(
        lambda s, i, acc: s.op("less", i, 5.0),
        lambda s, i, acc: (s.op("add", i, 1.0), s.op("add", acc, i)),
        i0, acc0)
    r = acc.eval({"i0": np.float32(0), "acc0": np.float32(0)})
    assert float(r) == 10.0          # 0+1+2+3+4


def test_scan_rnn_matches_unrolled():
    """The VERDICT #5 acceptance test: a scan-built RNN agrees (values and
    gradients) with the same recurrence unrolled op-by-op."""
    rng = np.random.default_rng(0)
    B, T, F, H = 3, 4, 5, 2
    xs_np = rng.standard_normal((T, B, F)).astype(np.float32)
    w_np = rng.standard_normal((F, H)).astype(np.float32) * 0.3
    rw_np = rng.standard_normal((H, H)).astype(np.float32) * 0.3

    def build(scan: bool):
        sd = SameDiff.create()
        xs = sd.placeholder("xs", (T, B, F))
        w = sd.var("w", w_np)
        rw = sd.var("rw", rw_np)
        h0 = sd.constant("h0", np.zeros((B, H), np.float32))
        if scan:
            h, _ = sd.scan(
                lambda s, h, x, wv, rwv: (
                    s.op("tanh", s.op("add", s.op("matmul", x, wv),
                                      s.op("matmul", h, rwv))),) * 2,
                h0, xs, consts=(w, rw))
        else:
            h = h0
            for t in range(T):
                xt = sd.op("squeeze", sd.op("slice", xs, begin=[t, 0, 0],
                                            size=[1, B, F]), axis=0)
                h = sd.op("tanh", sd.op("add", sd.op("matmul", xt, w),
                                        sd.op("matmul", h, rw)))
        sd.op("sum", sd.op("square", h), name="loss")
        sd.set_loss_variables("loss")
        return sd

    sd_scan, sd_unroll = build(True), build(False)
    feeds = {"xs": xs_np}
    v1 = sd_scan.output(feeds, "loss")["loss"]
    v2 = sd_unroll.output(feeds, "loss")["loss"]
    np.testing.assert_allclose(v1, v2, rtol=1e-5)
    g1 = sd_scan.calculate_gradients(feeds, "w", "rw")
    g2 = sd_unroll.calculate_gradients(feeds, "w", "rw")
    np.testing.assert_allclose(g1["w"], g2["w"], rtol=1e-4)
    np.testing.assert_allclose(g1["rw"], g2["rw"], rtol=1e-4)


def test_control_flow_save_load_roundtrip(tmp_path):
    sd = SameDiff.create()
    x = sd.placeholder("x", (3,))
    p = sd.placeholder("p", ())
    c = sd.cond(p, lambda s, a: s.op("mul", a, 3.0),
                lambda s, a: s.op("sub", a, 1.0), x, name="branch")
    cf, ys = sd.scan(lambda s, carry, step: (s.op("add", carry, step),) * 2,
                     sd.constant("z", np.float32(0)), x)
    path = str(tmp_path / "cf.sdz")
    sd.save(path)
    sd2 = SameDiff.load(path)
    xs = np.array([1., 2., 3.], np.float32)
    for feeds in ({"x": xs, "p": 1.0}, {"x": xs, "p": 0.0}):
        a = sd.output(feeds, "branch")["branch"]
        b = sd2.output(feeds, "branch")["branch"]
        np.testing.assert_allclose(a, b)
    np.testing.assert_allclose(sd2.output({"x": xs}, cf.name)[cf.name], 6.0)


def test_scan_training_decreases_loss():
    """Train the scan-RNN end-to-end: gradients flow through lax.scan."""
    rng = np.random.default_rng(1)
    B, T, F, H = 8, 6, 4, 3
    xs_np = rng.standard_normal((T, B, F)).astype(np.float32)
    y_np = rng.standard_normal((B, H)).astype(np.float32)
    sd = SameDiff.create()
    xs = sd.placeholder("xs", (T, B, F))
    lab = sd.placeholder("lab", (B, H))
    w = sd.var("w", "XAVIER", F, H)
    rw = sd.var("rw", "XAVIER", H, H)
    h0 = sd.constant("h0", np.zeros((B, H), np.float32))
    h, _ = sd.scan(
        lambda s, h, x, wv, rwv: (
            s.op("tanh", s.op("add", s.op("matmul", x, wv),
                              s.op("matmul", h, rwv))),) * 2,
        h0, xs, consts=(w, rw))
    sd.loss.mean_squared_error(lab, h, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=Adam(0.05), data_set_feature_mapping=["xs"],
        data_set_label_mapping=["lab"]))
    sd.fit(xs_np, y_np)
    first = sd.score()
    for _ in range(30):
        sd.fit(xs_np, y_np)
    assert sd.score() < first * 0.5


def test_cross_scope_variable_rejected():
    sd = SameDiff.create()
    x = sd.placeholder("x", (3,))
    w = sd.var("w", np.ones(3, np.float32))
    with pytest.raises(ValueError, match="different SameDiff scope"):
        sd.cond(1.0, lambda s, a: s.op("mul", a, w),
                lambda s, a: a, x)


def test_extended_op_coverage():
    """Spot-check the extended declarable-op set through the graph engine
    (reference: generic op CustomOpTests)."""
    rng = np.random.default_rng(0)
    a_np = rng.standard_normal((4, 4)).astype(np.float32)
    spd = a_np @ a_np.T + 4 * np.eye(4, dtype=np.float32)

    sd = SameDiff.create()
    a = sd.var("a", a_np)
    s = sd.var("s", spd)
    data = sd.var("data", rng.standard_normal((6, 3)).astype(np.float32))
    ids = sd.constant("ids", np.array([0, 0, 1, 2, 1, 0]))

    vs = [sd.op("sort", a, axis=-1),
          sd.op("tril", a),
          sd.op("trace", a),
          sd.op("cholesky", s),
          sd.op("matrix_inverse", s),
          sd.op("segment_sum", data, ids, num_segments=3),
          sd.op("l2_normalize", a, axis=-1),
          sd.op("cumprod", a, axis=1),
          sd.op("squared_difference", a, a.mul(2.0)),
          sd.op("mish", a)]
    outs = sd.output({}, *vs)
    vals = [np.asarray(outs[v.name]) for v in vs]
    np.testing.assert_allclose(vals[0], np.sort(a_np, -1), rtol=1e-6)
    np.testing.assert_allclose(vals[1], np.tril(a_np))
    np.testing.assert_allclose(vals[2], np.trace(a_np), rtol=1e-6)
    L = vals[3]
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(vals[4] @ spd, np.eye(4), atol=1e-4)
    assert vals[5].shape == (3, 3)
    np.testing.assert_allclose(np.linalg.norm(vals[6], axis=-1),
                               1.0, rtol=1e-5)
    np.testing.assert_allclose(vals[7], np.cumprod(a_np, 1), rtol=1e-5)
    np.testing.assert_allclose(vals[8], a_np * a_np, rtol=1e-5)
    assert np.isfinite(vals[9]).all()


def test_scatter_and_gather_nd():
    sd = SameDiff.create()
    base = sd.var("base", np.zeros((5, 2), np.float32))
    upd = sd.constant("upd", np.ones((2, 2), np.float32))
    idx = sd.constant("idx", np.array([1, 3]))
    out = sd.op("scatter_add", base, idx, upd)
    r = np.asarray(out.eval({}))
    assert r[1].sum() == 2 and r[3].sum() == 2 and r[0].sum() == 0


def test_samediff_evaluate_iterator():
    """Reference `sd.evaluate(DataSetIterator, output, Evaluation)`."""
    from deeplearning4j_tpu.data.dataset import DataSet

    rng = np.random.default_rng(4)
    x_all = rng.standard_normal((60, 4)).astype(np.float32)
    labels = ((x_all[:, 0] > 0).astype(int)
              + (x_all[:, 1] > 0).astype(int))
    y_all = np.eye(3, dtype=np.float32)[labels]

    class It:
        def reset(self):
            pass

        def __iter__(self):
            for i in range(0, 60, 20):
                yield DataSet(x_all[i:i + 20], y_all[i:i + 20])

    sd = _mlp_sd()
    sd.set_training_config(TrainingConfig(
        updater=Adam(0.05), data_set_feature_mapping=["input"],
        data_set_label_mapping=["label"]))
    for _ in range(60):
        sd.fit(x_all, y_all)
    ev = sd.evaluate(It(), "out")
    assert ev.accuracy() > 0.85
    assert ev.confusion.sum() == 60


def test_extended_namespaces():
    """SDBitwise/SDImage/SDLinalg/SDRandom (reference codegen'd namespace
    classes over the declarable registry)."""
    sd = SameDiff.create()
    a = sd.constant("a", np.array([0b1100, 0b1010], np.int32))
    b = sd.constant("b", np.array([0b1010, 0b0110], np.int32))
    x = sd.var("x", np.random.RandomState(0).rand(1, 4, 4, 3)
               .astype(np.float32))
    spd = np.array([[4.0, 1.0], [1.0, 3.0]], np.float32)
    s = sd.var("s", spd)

    v_and = sd.bitwise.bitwise_and(a, b)
    v_img = sd.image.rgb_to_hsv(x)
    v_chol = sd.linalg.cholesky(s)
    v_rand = sd.random.uniform(2.0, 5.0, (8,))
    outs = sd.output({}, v_and, v_img, v_chol, v_rand)
    np.testing.assert_array_equal(outs[v_and.name], [0b1000, 0b0010])
    assert outs[v_img.name].shape == (1, 4, 4, 3)
    L = np.asarray(outs[v_chol.name])
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4)
    r = np.asarray(outs[v_rand.name])
    assert r.shape == (8,) and (r >= 2.0).all() and (r < 5.0).all()

    # namespaces are scoped: math ops don't leak into bitwise
    import pytest as _pytest
    with _pytest.raises(AttributeError):
        sd.bitwise.cholesky


def test_random_sites_draw_independent_streams():
    """Two random nodes sharing the per-step key must not produce identical
    samples (code-review r2: per-site key folding)."""
    sd = SameDiff.create()
    a = sd.random.normal(0.0, 1.0, (8,))
    b = sd.random.normal(0.0, 1.0, (8,))
    outs = sd.output({}, a, b)
    va, vb = np.asarray(outs[a.name]), np.asarray(outs[b.name])
    assert not np.allclose(va, vb)


def test_rng_tags_survive_save_load():
    """Stochastic nodes added after load() must not reuse existing tags
    (code-review r2)."""
    sd = SameDiff.create()
    a = sd.random.normal(0.0, 1.0, (8,))
    sd.save("/tmp/_rng_tags.zip")
    sd2 = SameDiff.load("/tmp/_rng_tags.zip")
    b = sd2.random.normal(0.0, 1.0, (8,))
    outs = sd2.output({}, a.name, b.name)
    assert not np.allclose(np.asarray(outs[a.name]),
                           np.asarray(outs[b.name]))


def test_sd_fit_steps_matches_sequential():
    """SameDiff.fit_steps == k sequential fit() calls, bit-exact."""
    import jax

    def build():
        sd = _mlp_sd()
        sd.set_training_config(TrainingConfig(
            updater=Adam(1e-2),
            data_set_feature_mapping=["input"],
            data_set_label_mapping=["label"]))
        return sd

    x, y = _toy()
    k = 5
    a, b = build(), build()
    for _ in range(k):
        a.fit(x, y)
    feeds = {"input": np.broadcast_to(x, (k,) + x.shape).copy(),
             "label": np.broadcast_to(y, (k,) + y.shape).copy()}
    losses = b.fit_steps(feeds)
    assert losses.shape == (k,)
    for la, lb in zip(jax.tree_util.tree_leaves(a.variables_),
                      jax.tree_util.tree_leaves(b.variables_)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert a.iteration == b.iteration == k
    assert abs(a.score() - b.score()) < 1e-7


def test_sd_fit_steps_rng_path_matches_sequential():
    """fit_steps through a graph WITH dropout (the has_rng step branch):
    the scan must split the carry key exactly like sequential fit."""
    import jax

    def build():
        sd = SameDiff.create()
        x = sd.placeholder("input", shape=(-1, 4))
        y = sd.placeholder("label", shape=(-1, 3))
        w0 = sd.var("w0", "XAVIER", 4, 16)
        b0 = sd.var("b0", np.zeros(16, np.float32))
        w1 = sd.var("w1", "XAVIER", 16, 3)
        b1 = sd.var("b1", np.zeros(3, np.float32))
        h = sd.nn.tanh(sd.nn.linear(x, w0, b0))
        h = sd.nn.dropout(h, p=0.3)
        logits = sd.nn.linear(h, w1, b1, name="logits")
        sd.loss.softmax_cross_entropy(y, logits, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(TrainingConfig(
            updater=Adam(1e-2),
            data_set_feature_mapping=["input"],
            data_set_label_mapping=["label"]))
        return sd

    x, y = _toy()
    k = 4
    a, b = build(), build()
    for _ in range(k):
        a.fit(x, y)
    feeds = {"input": np.broadcast_to(x, (k,) + x.shape).copy(),
             "label": np.broadcast_to(y, (k,) + y.shape).copy()}
    b.fit_steps(feeds)
    for la, lb in zip(jax.tree_util.tree_leaves(a.variables_),
                      jax.tree_util.tree_leaves(b.variables_)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_sd_fit_iterator_fused_matches_sequential():
    """sd.fit(iterator=..., fused_steps=2) == plain iterator fit."""
    import jax
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator

    rng = np.random.RandomState(5)
    batches = [DataSet(rng.rand(8, 4).astype(np.float32),
                       np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)])
               for _ in range(5)]          # 5 batches -> 2 blocks + tail

    def build():
        sd = _mlp_sd()
        sd.set_training_config(TrainingConfig(
            updater=Adam(1e-2),
            data_set_feature_mapping=["input"],
            data_set_label_mapping=["label"]))
        return sd

    a, b = build(), build()
    a.fit(iterator=ListDataSetIterator(batches), epochs=2)
    b.fit(iterator=ListDataSetIterator(batches), epochs=2, fused_steps=2)
    assert a.iteration == b.iteration == 10
    for la, lb in zip(jax.tree_util.tree_leaves(a.variables_),
                      jax.tree_util.tree_leaves(b.variables_)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
