"""OpValidation specs, part 1: elementwise / reductions / shape / linalg /
losses / special functions (reference OpValidation case corpus:
`platform-tests/.../nd4j/autodiff/opvalidation/*.java` — goldens here are
independent numpy/scipy closed forms, NOT re-derivations of the op impls)."""
import numpy as np
import scipy.special as ss

from deeplearning4j_tpu.autodiff.validation import OpTestCase

rs = np.random.RandomState(1234)


def F(*s, lo=-2.0, hi=2.0):
    """float32 tensor arg in [lo, hi)."""
    return rs.uniform(lo, hi, s).astype(np.float32)


def FP(*s, lo=0.1, hi=2.0):
    return rs.uniform(lo, hi, s).astype(np.float32)


def F01(*s):
    return rs.uniform(0.05, 0.95, s).astype(np.float32)


def I32(*s, lo=0, hi=10):
    return rs.randint(lo, hi, s).astype(np.int32)


def PSD(n):
    a = rs.uniform(-1, 1, (n, n)).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def C(op, *args, g=None, kw=None, grad=(), grad_sample=0, tol=1e-5,
      gtol=5e-3, check=None, jit=True, custom=None, tag=""):
    return OpTestCase(op=op, args=args, kwargs=kw or {}, golden=g,
                      grad=grad, grad_sample=grad_sample, tol=tol,
                      gtol=gtol, check=check, jit=jit, custom=custom,
                      tag=tag)


CASES = []
_a, _b = F(3, 4), F(3, 4)
_pos = FP(3, 4)

# ---- elementwise arithmetic ----
CASES += [
    C("add", _a, _b, g=lambda a, b: a + b, grad=(0, 1)),
    C("sub", _a, _b, g=lambda a, b: a - b, grad=(0, 1)),
    C("mul", _a, _b, g=lambda a, b: a * b, grad=(0, 1)),
    C("div", _a, _pos, g=lambda a, b: a / b, grad=(0, 1)),
    C("rsub", _a, _b, g=lambda a, b: b - a, grad=(0, 1)),
    C("rdiv", _pos, _a, g=lambda a, b: b / a, grad=(0, 1)),
    C("pow", FP(3, 4), F(3, 4, lo=-1.5, hi=1.5),
      g=lambda a, b: a ** b, grad=(0, 1)),
    C("neg", _a, g=lambda a: -a, grad=(0,)),
    C("abs", FP(3, 4), g=np.abs, grad=(0,)),
    C("exp", _a, g=np.exp, grad=(0,)),
    C("log", _pos, g=np.log, grad=(0,)),
    C("log1p", _pos, g=np.log1p, grad=(0,)),
    C("sqrt", _pos, g=np.sqrt, grad=(0,)),
    C("square", _a, g=lambda a: a * a, grad=(0,)),
    C("cube", _a, g=lambda a: a ** 3, grad=(0,)),
    C("reciprocal", _pos, g=lambda a: 1.0 / a, grad=(0,)),
    C("sign", _a, g=np.sign),
    C("floor", _a, g=np.floor),
    C("ceil", _a, g=np.ceil),
    C("round", _a, g=np.round),
    C("rint", _a, g=np.rint),
    C("trunc", _a, g=np.trunc),
    C("clip", F(3, 4), g=lambda a, lo=None, hi=None: np.clip(a, lo, hi),
      kw={"lo": -0.5, "hi": 0.5}),
    C("maximum", _a, _b, g=np.maximum, grad=(0, 1)),
    C("minimum", _a, _b, g=np.minimum, grad=(0, 1)),
    C("expm1", _a, g=np.expm1, grad=(0,)),
    C("rsqrt", _pos, g=lambda a: 1.0 / np.sqrt(a), grad=(0,)),
    C("cbrt", FP(3, 4), g=np.cbrt, grad=(0,)),
    C("mod", F(3, 4), FP(3, 4), g=np.mod),
    C("fmod", F(3, 4), FP(3, 4), g=np.fmod),
    C("remainder", F(3, 4), FP(3, 4), g=np.remainder),
    C("reverse_mod", FP(3, 4), F(3, 4), g=lambda a, b: b % a),
    C("truncate_div", F(3, 4), FP(3, 4),
      g=lambda a, b: np.trunc(a / b).astype(np.float32)),
    C("floor_div", I32(3, 4, lo=1, hi=9), I32(3, 4, lo=1, hi=4),
      g=np.floor_divide),
    C("real_div", _a, _pos, g=lambda a, b: a / b, grad=(0, 1)),
    C("divide_no_nan",
      F(4), np.asarray([0.0, 2.0, 0.0, -1.5], np.float32),
      g=lambda a, b: np.where(b == 0, 0.0, a / np.where(b == 0, 1.0, b))),
    # grad config: denominators bounded away from the b=0 jump (where the
    # zero-substitution makes FD meaningless by design)
    C("divide_no_nan", F(4), FP(4, lo=0.5, hi=2.0),
      g=lambda a, b: a / b, grad=(0, 1), tag="grad"),
    C("squared_difference", _a, _b, g=lambda a, b: (a - b) ** 2,
      grad=(0, 1)),
    C("axpy", np.float32(1.7), F(3), F(3),
      g=lambda al, x, y: al * x + y, grad=(1, 2)),
    C("hypot", _a, _b, g=np.hypot, grad=(0, 1)),
    C("atan2", _a, _pos, g=np.arctan2, grad=(0, 1)),
    C("xlogy", FP(3, 4), FP(3, 4), g=ss.xlogy, grad=(0, 1)),
    C("sinc", FP(3, 4), g=np.sinc, grad=(0,)),
]

# ---- trig / hyperbolic ----
_sm = F(2, 3, lo=-0.9, hi=0.9)
CASES += [
    C("sin", _a, g=np.sin, grad=(0,)),
    C("cos", _a, g=np.cos, grad=(0,)),
    C("tan", _sm, g=np.tan, grad=(0,)),
    C("asin", _sm, g=np.arcsin, grad=(0,)),
    C("acos", _sm, g=np.arccos, grad=(0,)),
    C("atan", _a, g=np.arctan, grad=(0,)),
    C("sinh", _a, g=np.sinh, grad=(0,)),
    C("cosh", _a, g=np.cosh, grad=(0,)),
    C("tanh", _a, g=np.tanh, grad=(0,)),
    C("asinh", _a, g=np.arcsinh, grad=(0,)),
    C("acosh", FP(2, 3, lo=1.2, hi=3.0), g=np.arccosh, grad=(0,)),
    C("atanh", _sm, g=np.arctanh, grad=(0,)),
    C("to_degrees", _a, g=np.degrees, grad=(0,)),
    C("to_radians", _a, g=np.radians, grad=(0,)),
]

# ---- comparisons / logic ----
_bo = rs.rand(3, 4) > 0.5
_bo2 = rs.rand(3, 4) > 0.5
CASES += [
    C("less", _a, _b, g=np.less),
    C("less_equal", _a, _b, g=np.less_equal),
    C("greater", _a, _b, g=np.greater),
    C("greater_equal", _a, _b, g=np.greater_equal),
    C("equal", I32(3, 4, hi=3), I32(3, 4, hi=3), g=np.equal),
    C("not_equal", I32(3, 4, hi=3), I32(3, 4, hi=3), g=np.not_equal),
    C("eq", I32(3, 4, hi=3), I32(3, 4, hi=3), g=np.equal),
    C("neq", I32(3, 4, hi=3), I32(3, 4, hi=3), g=np.not_equal),
    C("gt", _a, _b, g=np.greater),
    C("gte", _a, _b, g=np.greater_equal),
    C("lt", _a, _b, g=np.less),
    C("lte", _a, _b, g=np.less_equal),
    C("logical_and", _bo, _bo2, g=np.logical_and),
    C("logical_or", _bo, _bo2, g=np.logical_or),
    C("logical_not", _bo, g=np.logical_not),
    C("where", _bo, _a, _b, g=np.where),
    C("select", _bo, _a, _b, g=np.where),
    C("isnan", np.asarray([1.0, np.nan, np.inf], np.float32), g=np.isnan),
    C("isinf", np.asarray([1.0, np.nan, np.inf], np.float32), g=np.isinf),
    C("is_finite", np.asarray([1.0, np.nan, np.inf], np.float32),
      g=np.isfinite),
    C("is_finite_all", np.asarray([1.0, 2.0], np.float32),
      g=lambda a: np.asarray(True)),
    C("isclose", _a, _a + 1e-7, g=lambda a, b: np.isclose(a, b)),
    C("equals_with_eps", _a, _a, g=lambda a, b, eps=1e-5:
      np.asarray(True)),
    C("compare_and_set", np.asarray([1.0, 2.0, 1.0], np.float32),
      g=lambda a, compare, set_val, eps=1e-7:
      np.where(np.abs(a - compare) < eps, set_val, a),
      kw={"compare": 1.0, "set_val": 9.0}),
    C("assign", _a, np.float32(3.5),
      g=lambda a, b: np.full_like(a, 3.5)),
    C("assign_add", _a, _b, g=lambda a, b: a + b, grad=(0, 1)),
    C("assign_sub", _a, _b, g=lambda a, b: a - b, grad=(0, 1)),
    C("is_non_decreasing", np.asarray([1.0, 1.0, 2.0], np.float32),
      g=lambda a: np.asarray(True)),
    C("is_strictly_increasing", np.asarray([1.0, 1.0, 2.0], np.float32),
      g=lambda a: np.asarray(False)),
    C("is_numeric_tensor", _a, jit=False,
      check=lambda out: np.testing.assert_array_equal(out[0], True)),
]

# ---- reductions ----
_r = F(3, 4, 5)
CASES += [
    C("sum", _r, g=lambda a, axis=None, keepdims=False:
      np.sum(a, axis=axis, keepdims=keepdims), kw={"axis": 1}, grad=(0,)),
    C("sum", _r, g=lambda a, **k: np.sum(a), tag="all"),
    C("mean", _r, g=lambda a, axis=None, keepdims=False:
      np.mean(a, axis=axis, keepdims=keepdims),
      kw={"axis": (0, 2), "keepdims": True}, grad=(0,)),
    C("max", _r, g=lambda a, axis=None, keepdims=False:
      np.max(a, axis=axis, keepdims=keepdims), kw={"axis": 2}),
    C("min", _r, g=lambda a, axis=None, keepdims=False:
      np.min(a, axis=axis, keepdims=keepdims), kw={"axis": 0}),
    C("prod", F(3, 4), g=lambda a, axis=None, keepdims=False:
      np.prod(a, axis=axis, keepdims=keepdims), kw={"axis": 1}, grad=(0,)),
    C("std", _r, g=lambda a, axis=None, keepdims=False, ddof=0:
      np.std(a, axis=axis, keepdims=keepdims, ddof=ddof),
      kw={"axis": 1, "ddof": 1}, tol=1e-4),
    C("var", _r, g=lambda a, axis=None, keepdims=False, ddof=0:
      np.var(a, axis=axis, keepdims=keepdims, ddof=ddof),
      kw={"axis": 1}, grad=(0,), tol=1e-4),
    C("norm2", _r, g=lambda a, axis=None, keepdims=False:
      np.sqrt(np.sum(a * a, axis=axis, keepdims=keepdims)),
      kw={"axis": 2}, grad=(0,)),
    C("norm1", _r, g=lambda a, axis=None, keepdims=False:
      np.sum(np.abs(a), axis=axis, keepdims=keepdims), kw={"axis": 1}),
    C("norm_max", _r, g=lambda a, axis=None, keepdims=False:
      np.max(np.abs(a), axis=axis, keepdims=keepdims), kw={"axis": 1}),
    C("norm_p", FP(3, 4), g=lambda a, p=2, axis=None, keepdims=False:
      np.sum(np.abs(a) ** p, axis=axis, keepdims=keepdims) ** (1.0 / p),
      kw={"p": 3, "axis": 1}, tol=1e-4),
    C("norm_fro", F(4, 4), g=np.linalg.norm, grad=(0,)),
    C("amax", _r, g=lambda a, axis=None, **k: np.max(np.abs(a), axis=axis),
      kw={"axis": 1}),
    C("amin", _r, g=lambda a, axis=None, **k: np.min(np.abs(a), axis=axis),
      kw={"axis": 1}),
    C("asum", _r, g=lambda a, axis=None, **k: np.sum(np.abs(a), axis=axis),
      kw={"axis": 1}),
    C("amean", _r, g=lambda a, axis=None, **k:
      np.mean(np.abs(a), axis=axis), kw={"axis": 1}),
    C("square_sum", _r, g=lambda a, axis=None, **k:
      np.sum(a * a, axis=axis), kw={"axis": 1}, grad=(0,)),
    C("argmax", _r, g=lambda a, axis=-1: np.argmax(a, axis=axis)),
    C("argmin", _r, g=lambda a, axis=-1: np.argmin(a, axis=axis)),
    C("logsumexp", _r, g=lambda a, axis=None, keepdims=False:
      ss.logsumexp(a, axis=axis, keepdims=keepdims), kw={"axis": 1},
      grad=(0,)),
    C("reduce_any", _bo, g=lambda a, axis=None, **k:
      np.any(a, axis=axis), kw={"axis": 1}),
    C("reduce_all", _bo, g=lambda a, axis=None, **k:
      np.all(a, axis=axis), kw={"axis": 1}),
    C("entropy", F01(3, 4), g=lambda a, axis=None:
      -np.sum(a * np.log(a), axis=axis), kw={"axis": 1}, grad=(0,)),
    C("log_entropy", F01(3, 4), g=lambda a, axis=None:
      np.log(-np.sum(a * np.log(a), axis=axis)), kw={"axis": 1}),
    C("shannon_entropy", F01(3, 4), g=lambda a, axis=None:
      -np.sum(a * np.log2(a), axis=axis), kw={"axis": 1}),
    C("zero_fraction", np.asarray([0.0, 1.0, 0.0, 2.0], np.float32),
      g=lambda a: np.float32(0.5)),
    C("count_nonzero", np.asarray([[0, 1], [2, 0]], np.float32),
      g=lambda a, axis=None: np.count_nonzero(a, axis=axis)),
    C("count_zero", np.asarray([[0, 1], [2, 0]], np.float32),
      g=lambda a, axis=None: np.sum(a == 0, axis=axis).astype(np.int32)),
    C("percentile", F(40), g=lambda a, q, axis=None, interpolation="linear":
      np.percentile(a, q, axis=axis, method=interpolation),
      kw={"q": 30.0}, tol=1e-4),
    C("median", F(3, 9), g=lambda a, axis=None:
      np.median(a, axis=axis), kw={"axis": 1}),
    C("nth_element", F(3, 8), g=lambda a, n, reverse=False:
      np.flip(np.sort(a, -1), -1)[..., n] if reverse
      else np.sort(a, -1)[..., n], kw={"n": 2, "reverse": True}),
    C("moments", _r, g=lambda a, axis=None, keepdims=False:
      (np.mean(a, axis=axis), np.var(a, axis=axis)), kw={"axis": 1},
      tol=1e-4),
    C("normalize_moments", np.float32(8.0), F(4), FP(4, lo=5.0, hi=9.0),
      g=lambda count, m_ss, v_ss, shift=0.0:
      (m_ss / count + shift, v_ss / count - (m_ss / count) ** 2),
      kw={"shift": 0.5}),
    C("sufficient_statistics", _r, g=lambda x, axes, shift=None:
      (np.float32(x.shape[1]), np.sum(x - shift, axis=1),
       np.sum((x - shift) ** 2, axis=1), np.float32(shift)),
      kw={"axes": 1, "shift": 0.5}),
]

# ---- cumulative / windowed ----
CASES += [
    C("cumsum", F(3, 4), g=lambda a, axis=0: np.cumsum(a, axis=axis),
      kw={"axis": 1}, grad=(0,)),
    C("cumprod", FP(3, 4), g=lambda a, axis=0: np.cumprod(a, axis=axis),
      kw={"axis": 1}, grad=(0,)),
    C("cummax", F(3, 4), g=lambda a, axis=0:
      np.maximum.accumulate(a, axis=axis), kw={"axis": 1}),
    C("cummin", F(3, 4), g=lambda a, axis=0:
      np.minimum.accumulate(a, axis=axis), kw={"axis": 1}),
    C("cumsum_ext", F(5), g=lambda a, axis=0, exclusive=False,
      reverse=False: np.flip(np.cumsum(np.flip(a)) - np.flip(a))
      if (exclusive and reverse) else None,
      kw={"exclusive": True, "reverse": True}),
    C("cumsum_ext", F(5), g=lambda a, axis=0, exclusive=False,
      reverse=False: np.concatenate([[0.0], np.cumsum(a)[:-1]]),
      kw={"exclusive": True}, tag="excl"),
    C("bincount", I32(20, hi=6), g=lambda a, length:
      np.bincount(a, minlength=length)[:length], kw={"length": 6}),
    C("histogram", F(30), g=lambda a, bins: np.histogram(a, bins=bins)[0],
      kw={"bins": 5}),
    C("histogram_fixed_width", F(30),
      g=lambda a, lo, hi, nbins=100:
      np.histogram(a, bins=nbins, range=(lo, hi))[0],
      kw={"lo": -2.0, "hi": 2.0, "nbins": 8}),
]

# ---- clipping ----
_big = F(3, 4, lo=-5, hi=5)
CASES += [
    C("clip_by_value", _big, g=lambda a, lo, hi: np.clip(a, lo, hi),
      kw={"lo": -1.0, "hi": 1.0}),
    C("clip_by_norm", _big, g=lambda a, clip_norm, axis=None:
      a * min(1.0, clip_norm / np.linalg.norm(a)), kw={"clip_norm": 2.0},
      tol=1e-4),
    C("clip_by_avg_norm", _big, g=lambda a, clip_norm:
      a * min(1.0, clip_norm / (np.linalg.norm(a) / a.size)),
      kw={"clip_norm": 0.1}, tol=1e-4),
    C("clip_by_global_norm", F(3), F(4),
      g=lambda cap, x, y: tuple(
          v * min(1.0, cap / np.sqrt(np.sum(x * x) + np.sum(y * y)))
          for v in (x, y)),
      kw={}, tag="pair", tol=1e-4),
]
# first positional arg of clip_by_global_norm is the cap (static float)
CASES[-1] = C("clip_by_global_norm", np.float32(1.5), F(3), F(4),
              g=lambda cap, x, y: tuple(
                  v * min(1.0, 1.5 / np.sqrt(np.sum(x * x)
                                             + np.sum(y * y)))
                  for v in (x, y)), tol=1e-4)

# ---- shape / layout ----
_m = F(3, 4)
_t3 = F(2, 3, 4)
CASES += [
    C("matmul", F(3, 4), F(4, 5), g=np.matmul, grad=(0, 1)),
    C("mmul", F(3, 4), F(4, 5), g=np.matmul, grad=(0, 1)),
    C("batched_matmul", F(2, 3, 4), F(2, 4, 5), g=np.matmul, grad=(0, 1), grad_sample=16),
    C("tensordot", F(2, 3, 4), F(3, 4, 5),
      g=lambda a, b, axes=2: np.tensordot(a, b, axes), grad=(0, 1), grad_sample=16),
    C("transpose", _t3, g=lambda a, perm=None: np.transpose(a, perm),
      kw={"perm": (2, 0, 1)}),
    C("permute", _t3, (1, 2, 0), g=lambda a, p: np.transpose(a, p)),
    C("reshape", _t3, (4, 6), g=lambda a, s: np.reshape(a, s)),
    C("expand_dims", _m, g=lambda a, axis=0: np.expand_dims(a, axis),
      kw={"axis": 1}),
    C("squeeze", F(3, 1, 4), g=lambda a, axis=None:
      np.squeeze(a, axis), kw={"axis": 1}),
    C("concat", _m, F(2, 4), g=lambda a, b, axis=0:
      np.concatenate([a, b], axis), kw={"axis": 0}),
    C("stack", _m, F(3, 4), g=lambda a, b, axis=0:
      np.stack([a, b], axis), kw={"axis": 1}),
    C("unstack_at", _t3, g=lambda a, index=0, axis=0:
      np.take(a, 1, axis=1), kw={"index": 1, "axis": 1}),
    C("unstack", _t3, g=lambda a, axis=0:
      tuple(a[i] for i in range(a.shape[0]))),
    C("tile", _m, (2, 3), g=lambda a, r: np.tile(a, r)),
    C("slice", _t3, (0, 1, 2), (2, 2, 2),
      g=lambda a, b, s: a[0:2, 1:3, 2:4]),
    C("strided_slice", _t3, (0, 1, 0), (2, 3, 4), (1, 1, 2),
      g=lambda a, b, e, s: a[0:2, 1:3, 0:4:2]),
    C("gather", F(5, 3), I32(4, hi=5), g=lambda a, i, axis=0:
      np.take(a, i, axis=axis), grad=(0,)),
    C("gather_nd", F(4, 5), np.asarray([[0, 1], [3, 2]], np.int32),
      g=lambda a, i: a[i[:, 0], i[:, 1]]),
    C("take_along_axis", F(3, 5), I32(3, 2, hi=5),
      g=lambda a, i, axis=-1: np.take_along_axis(a, i, axis=axis)),
    C("one_hot", I32(4, hi=5), g=lambda i, depth, dtype="float32":
      np.eye(depth, dtype=np.float32)[i], kw={"depth": 5}),
    C("cast", _m, g=lambda a, dtype: a.astype(dtype),
      kw={"dtype": "int32"}),
    C("shape_of", _t3, g=lambda a: np.asarray(a.shape, np.int32)),
    C("size_of", _t3, g=lambda a: np.asarray(a.size, np.int32)),
    C("rank_of", _t3, g=lambda a: np.asarray(a.ndim, np.int32)),
    C("size_at", _t3, jit=False, kw={"dim": 1},
      check=lambda out: np.testing.assert_array_equal(out[0], 3)),
    C("zeros_like", _m, g=np.zeros_like),
    C("zeros_rows_like", _m, kw={"n": 5},
      g=lambda a, n: np.zeros((a.shape[0], n), a.dtype)),
    C("ones_like", _m, g=np.ones_like),
    C("fill_like", _m, g=lambda a, value: np.full_like(a, value),
      kw={"value": 2.5}),
    C("eye_like", F(3, 5), g=lambda a: np.eye(3, 5, dtype=np.float32)),
    C("eye", g=lambda n, m=None, dtype="float32": np.eye(n, dtype=np.float32),
      kw={"n": 4}, jit=False),
    C("pad", _m, ((1, 0), (0, 2)),
      g=lambda a, p, value=0.0: np.pad(a, p, constant_values=value),
      kw={"value": 1.5}),
    C("pad_mode", _m, ((1, 1), (2, 0)),
      g=lambda a, p, mode="constant", value=0.0: np.pad(a, p, mode="reflect"),
      kw={"mode": "reflect"}),
    C("mirror_pad", _m, ((1, 1), (1, 1)),
      g=lambda a, p, mode="REFLECT": np.pad(a, p, mode="symmetric"),
      kw={"mode": "SYMMETRIC"}),
    C("identity", _m, g=lambda a: a, grad=(0,)),
    C("broadcast_to", F(1, 4), (3, 4),
      g=lambda a, s: np.broadcast_to(a, s)),
    C("repeat", _m, g=lambda a, repeats, axis=None:
      np.repeat(a, repeats, axis), kw={"repeats": 2, "axis": 1}),
    C("flip", _t3, g=lambda a, axis=None: np.flip(a, axis),
      kw={"axis": 1}),
    C("reverse", _t3, (0, 2), g=lambda a, ax: np.flip(a, ax)),
    C("roll", _m, g=lambda a, shift, axis=None:
      np.roll(a, shift, axis), kw={"shift": 2, "axis": 1}),
    C("swap_axes", _t3, 0, 2, g=lambda a, i, j: np.swapaxes(a, i, j)),
    C("swap_last2", _t3, g=lambda a: np.swapaxes(a, -1, -2)),
    C("moveaxis", _t3, 0, 2, g=lambda a, s, d: np.moveaxis(a, s, d)),
    C("atleast_2d", F(5), g=np.atleast_2d),
    C("ravel", _t3, g=np.ravel),
    C("linspace", g=lambda start, stop, num=50:
      np.linspace(start, stop, num, dtype=np.float32),
      kw={"start": 0.0, "stop": 1.0, "num": 7}, jit=False, tol=1e-6),
    C("arange", g=lambda start, stop=None, step=1, dtype="float32":
      np.arange(start, stop, step, dtype=np.float32),
      kw={"start": 1.0, "stop": 7.0, "step": 2.0}, jit=False),
    C("full", g=lambda shape, value, dtype="float32":
      np.full(shape, value, np.float32),
      kw={"shape": (2, 3), "value": 1.5}, jit=False),
    C("meshgrid", F(3), F(4), g=lambda a, b, indexing="xy":
      tuple(np.meshgrid(a, b, indexing=indexing)), kw={"indexing": "ij"}),
    C("split_axis", F(7, 3), (3, 2, 2),
      g=lambda x, s, axis=0: (x[:3], x[3:5], x[5:])),
    C("split_equal", F(6, 3), 3,
      g=lambda x, n, axis=0: tuple(np.split(x, n, 0))),
    C("sequence_mask", np.asarray([1, 3, 0], np.int32),
      g=lambda l, maxlen, dtype="float32":
      (np.arange(maxlen)[None, :] < l[:, None]).astype(np.float32),
      kw={"maxlen": 4}),
    C("reverse_sequence", F(3, 5, 2), np.asarray([2, 5, 3], np.int32),
      g=lambda a, lengths, seq_axis=1, batch_axis=0: np.stack([
          np.concatenate([a[i, :n][::-1], a[i, n:]], 0)
          for i, n in enumerate(lengths)])),
    C("invert_permutation", np.asarray([2, 0, 1, 3], np.int32),
      g=lambda p: np.argsort(p)),
    C("unravel_index", np.asarray([1, 7, 11], np.int32), (3, 4),
      g=lambda i, s: np.stack(np.unravel_index(i, s), 0)),
    C("stop_gradient", _m, g=lambda a: a),
    C("tri", g=lambda n, m=None, k=0: np.tri(n, m, k, dtype=np.float32),
      kw={"n": 4, "m": 5, "k": 1}, jit=False),
    C("tuple_get", jit=False, custom=lambda fn: np.testing.assert_allclose(
        fn((np.float32(1.0), np.float32(2.0)), 1), 2.0)),
]

# ---- sort / search ----
CASES += [
    C("sort", F(3, 6), g=lambda a, axis=-1, descending=False:
      -np.sort(-a, axis=axis) if descending else np.sort(a, axis=axis),
      kw={"descending": True}),
    C("argsort", F(3, 6), g=lambda a, axis=-1: np.argsort(a, axis=axis)),
    C("top_k", F(3, 8), g=lambda a, k=1:
      (np.sort(a, -1)[..., ::-1][..., :k],
       np.argsort(-a, -1, kind="stable")[..., :k]), kw={"k": 3}),
    C("searchsorted", np.sort(F(8)), F(5),
      g=lambda s, v: np.searchsorted(s, v)),
    C("bucketize", F(6), g=lambda x, boundaries:
      np.searchsorted(boundaries, x, side="right").astype(np.int32),
      kw={"boundaries": [-1.0, 0.0, 1.0]}),
    C("unique", np.asarray([3, 1, 3, 2, 1], np.int32),
      g=lambda a, size=None: np.asarray([1, 2, 3, 1, 1], np.int32),
      kw={"size": 5}),
    C("unique_with_counts", np.asarray([3, 1, 3, 2, 1], np.int32),
      g=lambda a, size=None: (np.asarray([1, 2, 3], np.int32),
                              np.asarray([2, 1, 2], np.int32)),
      kw={"size": 3}),
    C("setdiff1d", np.asarray([1, 2, 3, 4, 5], np.int32),
      np.asarray([2, 4], np.int32),
      g=lambda a, b, size=None: np.asarray([1, 3, 5], np.int32),
      kw={"size": 3}),
    C("nonzero", np.asarray([[0, 1], [2, 0]], np.float32),
      g=lambda a, size=None: np.stack(np.nonzero(a), -1),
      kw={"size": 2}),
    C("isin", I32(6, hi=5), np.asarray([1, 3], np.int32), g=np.isin),
    C("in_top_k", F(4, 6), I32(4, hi=6),
      g=lambda p, t, k=1: np.asarray(
          [np.sum(p[i] > p[i, t[i]]) < k for i in range(p.shape[0])]),
      kw={"k": 2}),
    C("is_max", F(3, 5), g=lambda a, axis=-1:
      (a == np.max(a, axis=axis, keepdims=True)).astype(a.dtype)),
    C("confusion_matrix", np.asarray([0, 1, 2, 1], np.int32),
      np.asarray([0, 2, 2, 1], np.int32),
      g=lambda l, p, num_classes, weights=None: np.asarray(
          [[1, 0, 0], [0, 1, 1], [0, 0, 1]], np.float32),
      kw={"num_classes": 3}),
]

# ---- linalg ----
_A4 = PSD(4)
_b4 = F(4, 2)
_sq = F(4, 4)
CASES += [
    C("cholesky", _A4, g=np.linalg.cholesky, tol=1e-4),
    C("solve", _A4, _b4, g=np.linalg.solve, tol=1e-4, grad=(0, 1),
      gtol=2e-2),
    C("triangular_solve", np.linalg.cholesky(_A4).astype(np.float32), _b4,
      g=lambda a, b, lower=True: np.linalg.solve(a, b), tol=1e-4),
    C("cholesky_solve", np.linalg.cholesky(_A4).astype(np.float32), _b4,
      g=lambda c, b: np.linalg.solve(_A4.astype(np.float64), b), tol=1e-3),
    C("lu_solve", _A4, _b4, g=np.linalg.solve, tol=1e-3),
    C("matrix_inverse", _A4, g=np.linalg.inv, tol=1e-4),
    C("matrix_determinant", _sq, g=np.linalg.det, tol=1e-4, grad=(0,),
      gtol=2e-2),
    C("log_matrix_determinant", _A4,
      g=lambda a: np.linalg.slogdet(a)[1], tol=1e-4),
    C("slogdet", _sq, g=np.linalg.slogdet, tol=1e-4),
    C("logdet", _A4, g=lambda a: np.log(np.linalg.det(a)), tol=1e-3),
    C("matrix_rank", _A4, g=lambda a: np.linalg.matrix_rank(a)),
    C("pinv", F(4, 3), g=np.linalg.pinv, tol=1e-4),
    C("lstsq", F(5, 3), F(5, 2),
      g=lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0], tol=1e-3),
    C("qr", F(4, 3), check=lambda out: (
        np.testing.assert_allclose(out[0] @ out[1],
                                   np.asarray(CASES_QR_IN), atol=1e-4),
        np.testing.assert_allclose(out[0].T @ out[0], np.eye(3),
                                   atol=1e-4))),
    C("svd", F(4, 3), check=lambda out: (
        np.testing.assert_allclose(
            out[0][:, :out[1].shape[0]] @ np.diag(out[1])
            @ out[2][:out[1].shape[0]],
            np.asarray(CASES_SVD_IN), atol=1e-4))),
    # grad config: jax defines the SVD JVP only for the reduced form
    C("svd", F(4, 3), kw={"full_matrices": False},
      check=lambda out: np.testing.assert_allclose(
          out[0] @ np.diag(out[1]) @ out[2],
          np.asarray(CASES_SVD_IN2), atol=1e-4),
      grad=(0,), gtol=5e-2, tag="reduced-grad"),
    C("eig_sym", _A4, check=lambda out: np.testing.assert_allclose(
        np.asarray(_A4, np.float64) @ out[1],
        out[1] * out[0][None, :], atol=1e-3)),
    C("lu", _sq, check=lambda out: np.testing.assert_allclose(
        out[0] @ out[1] @ out[2], np.asarray(_sq, np.float64),
        atol=1e-4)),
    C("expm", F(3, 3, lo=-0.5, hi=0.5),
      g=lambda a: __import__("scipy.linalg", fromlist=["expm"]).expm(
          a.astype(np.float64)), tol=1e-4),
    C("matrix_band_part", _sq, 1, 1, g=lambda a, lo, hi:
      np.triu(np.tril(a, 1), -1)),
    C("trace", _sq, g=np.trace, grad=(0,)),
    C("diag", F(4), g=np.diag),
    C("diag_part", _sq, g=np.diagonal),
    C("tril", _sq, g=lambda a, k=0: np.tril(a, k), kw={"k": 1}),
    C("triu", _sq, g=lambda a, k=0: np.triu(a, k), kw={"k": -1}),
    C("matrix_diag", F(2, 3), g=lambda d:
      np.stack([np.diag(d[i]) for i in range(d.shape[0])])),
    C("matrix_diag_part", F(2, 4, 4), g=lambda a:
      np.diagonal(a, axis1=-2, axis2=-1)),
    C("matrix_set_diag", F(3, 3), F(3), g=lambda a, d:
      np.where(np.eye(3, dtype=bool), d[None, :], a)),
    C("outer", F(3), F(4), g=np.outer, grad=(0, 1)),
    C("kron", F(2, 2), F(3, 2), g=np.kron),
    C("cross", F(3, 3), F(3, 3), g=np.cross, grad=(0, 1)),
    C("dot", F(4), F(4), g=np.dot, grad=(0, 1)),
    C("vdot", F(4), F(4), g=np.vdot, grad=(0, 1)),
    C("einsum", "ij,jk->ik", F(3, 4), F(4, 2),
      g=lambda eq, a, b: np.einsum(eq, a, b), grad=(1, 2)),
    C("gemm", F(3, 4), F(5, 4), F(3, 5),
      g=lambda a, b, c=None, alpha=1.0, beta=1.0, trans_a=0, trans_b=0:
      alpha * (a @ b.T) + beta * c, kw={"alpha": 0.5, "beta": 2.0,
                                        "trans_b": 1}, grad=(0, 1, 2)),
    C("xw_plus_b", F(3, 4), F(4, 2), F(2),
      g=lambda x, w, b: x @ w + b, grad=(0, 1, 2)),
    C("linear", F(3, 4), F(4, 2), F(2),
      g=lambda x, w, b=None: x @ w + b, grad=(0, 1, 2)),
    C("relu_layer", F(3, 4), F(4, 2), F(2),
      g=lambda x, w, b: np.maximum(x @ w + b, 0.0), grad=(0, 1, 2)),
    C("bias_add", F(3, 4), F(4), g=lambda x, b: x + b, grad=(0, 1)),
]
# fixed inputs for the qr/svd property checks above (case args are bound
# AFTER this module builds, so regenerate the same arrays by index)
CASES_QR_IN = [c for c in CASES if c.op == "qr"][0].args[0]
CASES_SVD_IN = [c for c in CASES if c.op == "svd"][0].args[0]
CASES_SVD_IN2 = [c for c in CASES if c.op == "svd"][1].args[0]

# ---- distances / reduce3 ----
_d1, _d2 = F(3, 5), F(3, 5)
CASES += [
    C("euclidean_distance", _d1, _d2, g=lambda a, b, axis=None:
      np.sqrt(np.sum((a - b) ** 2, axis=axis)), kw={"axis": 1},
      grad=(0, 1)),
    C("manhattan_distance", _d1, _d2, g=lambda a, b, axis=None:
      np.sum(np.abs(a - b), axis=axis), kw={"axis": 1}),
    C("cosine_similarity", _d1, _d2, g=lambda a, b, axis=-1, eps=0:
      np.sum(a * b, -1) / (np.linalg.norm(a, axis=-1)
                           * np.linalg.norm(b, axis=-1)), tol=1e-4,
      grad=(0, 1)),
    C("cosine_distance", _d1, _d2, g=lambda l, p, axis=-1, eps=0:
      np.mean(1.0 - np.sum(
          (l / np.linalg.norm(l, axis=-1, keepdims=True))
          * (p / np.linalg.norm(p, axis=-1, keepdims=True)), -1)),
      tol=1e-4, grad=(0, 1)),
    C("cosine_distance_loss", _d1, _d2, g=lambda p, l, axis=-1:
      np.mean(1.0 - np.sum(
          (l / np.linalg.norm(l, axis=-1, keepdims=True))
          * (p / np.linalg.norm(p, axis=-1, keepdims=True)), -1)),
      tol=1e-4),
    C("jaccard_distance", F01(3, 5), F01(3, 5), g=lambda a, b, axis=None:
      1.0 - np.sum(np.minimum(a, b), 1) / np.sum(np.maximum(a, b), 1),
      kw={"axis": 1}, tol=1e-4),
    C("hamming_distance", I32(3, 5, hi=3), I32(3, 5, hi=3),
      g=lambda a, b, axis=None: np.sum((a != b).astype(np.float32),
                                       axis=axis), kw={"axis": 1}),
    C("bits_hamming_distance", I32(6, hi=100), I32(6, hi=100),
      g=lambda a, b: np.sum([bin(int(x) ^ int(y)).count("1")
                             for x, y in zip(a, b)])),
    C("knn_mindistance", F(4), F(4, lo=3.0, hi=5.0), F(4, lo=2.0, hi=4.0),
      g=lambda lo, hi, p: np.sqrt(np.sum(np.maximum(
          np.maximum(lo - p, 0), np.maximum(p - hi, 0)) ** 2, -1))),
    C("cell_contains", F(3), np.float32(10.0), F(3),
      g=lambda c, w, p: np.all((p >= c - 5.0) & (p <= c + 5.0), -1)),
]

# ---- losses ----
_labels = np.eye(5, dtype=np.float32)[rs.randint(0, 5, 6)]
_logits = F(6, 5)
_probs = F01(6, 5)


def _np_softmax(x, axis=-1):
    e = np.exp(x - np.max(x, axis=axis, keepdims=True))
    return e / np.sum(e, axis=axis, keepdims=True)


CASES += [
    C("softmax_cross_entropy", _labels, _logits,
      g=lambda l, z, axis=-1: np.mean(-np.sum(
          l * np.log(_np_softmax(z)), -1)), grad=(1,), tol=1e-4),
    C("sparse_softmax_cross_entropy", I32(6, hi=5), _logits,
      g=lambda l, z: np.mean(-np.log(_np_softmax(z))[np.arange(6), l]),
      grad=(1,), tol=1e-4),
    C("sigmoid_cross_entropy", _labels, _logits,
      g=lambda l, z: np.mean(np.maximum(z, 0) - z * l
                             + np.log1p(np.exp(-np.abs(z)))),
      grad=(1,), tol=1e-4),
    C("weighted_cross_entropy_with_logits", _labels, _logits,
      np.float32(2.0),
      g=lambda l, z, w: np.mean((1 - l) * z + (1 + (w - 1) * l) * (
          np.log1p(np.exp(-np.abs(z))) + np.maximum(-z, 0))),
      tol=1e-4),
    C("mean_squared_error", _labels, _probs,
      g=lambda l, p: np.mean((l - p) ** 2), grad=(1,)),
    C("absolute_difference", _labels, _probs,
      g=lambda l, p: np.mean(np.abs(l - p))),
    C("l2_loss", _m, g=lambda a: 0.5 * np.sum(a * a), grad=(0,)),
    C("huber_loss", _labels, _probs * 3,
      g=lambda l, p, delta=1.0: np.mean(np.where(
          np.abs(l - p) <= delta, 0.5 * (l - p) ** 2,
          delta * (np.abs(l - p) - 0.5 * delta))), kw={"delta": 0.7},
      tol=1e-4),
    C("log_loss", _labels, _probs,
      g=lambda l, p, eps=1e-7: -np.mean(
          l * np.log(np.clip(p, eps, 1 - eps))
          + (1 - l) * np.log1p(-np.clip(p, eps, 1 - eps))), tol=1e-4),
    C("hinge_loss", _labels, _logits,
      g=lambda l, z: np.mean(np.maximum(0.0, 1.0 - (2 * l - 1) * z))),
    C("poisson_loss", FP(4, 3), FP(4, 3),
      g=lambda l, p, log_input=False, eps=1e-8:
      np.mean(p - l * np.log(p + eps)), tol=1e-4),
    C("log_poisson_loss", FP(4, 3), F(4, 3),
      g=lambda l, li, compute_full_loss=False:
      np.mean(np.exp(li) - l * li), tol=1e-4),
    C("kl_divergence", F01(4, 5), F01(4, 5),
      g=lambda l, p, eps=1e-12: np.mean(np.sum(
          l * (np.log(l) - np.log(p)), -1)), tol=1e-4, grad=(1,)),
    C("mean_pairwise_squared_error", F(3, 4), F(3, 4),
      g=lambda l, p: np.mean([
          np.mean([((p - l)[i, a] - (p - l)[i, b]) ** 2
                   for a in range(4) for b in range(4) if a != b])
          for i in range(3)]), tol=1e-3),
]

# ---- special functions ----
CASES += [
    C("erf", _a, g=ss.erf, grad=(0,)),
    C("erfc", _a, g=ss.erfc, grad=(0,)),
    C("erfinv", F(2, 3, lo=-0.9, hi=0.9), g=ss.erfinv, grad=(0,),
      tol=1e-4),
    C("digamma", FP(3, 4, lo=0.5, hi=4.0), g=ss.digamma, grad=(0,),
      tol=1e-4),
    C("lgamma", FP(3, 4, lo=0.5, hi=4.0), g=ss.gammaln, grad=(0,),
      tol=1e-4),
    C("betainc", FP(3, lo=0.5, hi=3.0), FP(3, lo=0.5, hi=3.0), F01(3),
      g=ss.betainc, tol=1e-4),
    C("zeta", FP(3, lo=1.5, hi=4.0), FP(3, lo=0.5, hi=2.0),
      g=lambda x, q: ss.zeta(x, q), tol=1e-3),
    C("igamma", FP(3, lo=0.5, hi=3.0), FP(3, lo=0.5, hi=3.0),
      g=ss.gammainc, tol=1e-4),
    C("igammac", FP(3, lo=0.5, hi=3.0), FP(3, lo=0.5, hi=3.0),
      g=ss.gammaincc, tol=1e-4),
    C("lbeta", FP(3, 4, lo=0.5, hi=3.0),
      g=lambda x: np.sum(ss.gammaln(x), -1) - ss.gammaln(np.sum(x, -1)),
      tol=1e-4),
    C("polyval", [2.0, -1.0, 3.0], F(4),
      g=lambda c, x: np.polyval(c, x), grad=(1,)),
]
# fix polygamma golden (the lambda-in-expression trick above is fragile)
CASES = [c for c in CASES if c.op != "polygamma"]
CASES.append(
    C("polygamma", np.asarray([1, 2, 3], np.int32),
      FP(3, lo=0.5, hi=4.0),
      g=lambda n, x: np.asarray([ss.polygamma(int(ni), float(xi))
                                 for ni, xi in zip(n, x)], np.float64),
      tol=1e-3))

# ---- signal / FFT ----
_f_sig = F(3, 8)
_c_sig = (rs.randn(3, 8) + 1j * rs.randn(3, 8)).astype(np.complex64)
CASES += [
    C("fft", _f_sig, g=lambda a, axis=-1: np.fft.fft(a, axis=axis),
      tol=1e-4),
    C("ifft", _c_sig, g=lambda a, axis=-1: np.fft.ifft(a, axis=axis),
      tol=1e-4),
    C("rfft", _f_sig, g=lambda a, axis=-1: np.fft.rfft(a, axis=axis),
      tol=1e-4),
    C("irfft", np.fft.rfft(_f_sig).astype(np.complex64),
      g=lambda a, n=None, axis=-1: np.fft.irfft(a, n=n, axis=axis),
      tol=1e-4),
    C("fft2", F(2, 4, 4), g=lambda a: np.fft.fft2(a), tol=1e-4),
    C("ifft2", (rs.randn(2, 4, 4) + 1j * rs.randn(2, 4, 4)).astype(
        np.complex64), g=lambda a: np.fft.ifft2(a), tol=1e-4),
]

# ---- bitwise ----
_i1, _i2 = I32(5, hi=200), I32(5, hi=200)
CASES += [
    C("bitwise_and", _i1, _i2, g=np.bitwise_and),
    C("bitwise_or", _i1, _i2, g=np.bitwise_or),
    C("bitwise_xor", _i1, _i2, g=np.bitwise_xor),
    C("bitwise_not", _i1, g=np.bitwise_not),
    C("toggle_bits", _i1, g=np.bitwise_not),
    C("shift_left", _i1, np.asarray([1, 2, 3, 1, 2], np.int32),
      g=np.left_shift),
    C("shift_right", _i1, np.asarray([1, 2, 3, 1, 2], np.int32),
      g=np.right_shift),
    C("cyclic_shift_left", _i1, 3, g=lambda a, n: (
        (a.astype(np.uint32) << np.uint32(3))
        | (a.astype(np.uint32) >> np.uint32(29))).astype(np.int32)),
    C("cyclic_shift_right", _i1, 3, g=lambda a, n: (
        (a.astype(np.uint32) >> np.uint32(3))
        | (a.astype(np.uint32) << np.uint32(29))).astype(np.int32)),
    C("population_count", _i1, g=lambda a: np.asarray(
        [bin(int(x) & 0xFFFFFFFF).count("1") for x in a], np.int32)),
    C("bitcast", np.asarray([1.0, -2.0], np.float32),
      g=lambda a, dtype: a.view(np.int32), kw={"dtype": "int32"}),
    C("compare_and_bitpack", F(2, 16), np.float32(0.0),
      g=lambda x, t: np.packbits((x > t).astype(np.uint8),
                                 axis=-1)),
]
