"""Hierarchical gradient-sharing TRAINING worker (spawned by test_comms
and `bench.py --comms` via LocalLauncher — NOT a pytest file).

Each rank builds the SAME small MLP, enables hierarchical gradient
sharing (config resolved from the launcher's `DL4J_TPU_*` env), and
trains on its own shard of one deterministic global data stream: the
compiled grad half reduces over the local mesh (ICI role), the host-side
exchange combines across ranks over TCP (DCN role), the compiled apply
half updates.  Mode "compressed" uses the threshold codec with
error-feedback residuals; "dense" ships raw f32 — the A/B baseline.

Per-rank outputs for the driver: the loss curve + final first-layer
weights (replica-consistency proof) as npz, and the exchange stats
(bytes on wire, compression ratio) as json."""
import json
import os
import sys

import numpy as np

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel.hierarchical import (
    HierarchicalGradientSharing)
from deeplearning4j_tpu.parallel.multihost import ENV_NPROC, ENV_PID
from deeplearning4j_tpu.train.updaters import Sgd

out_dir = sys.argv[1]
mode = sys.argv[2]                       # "compressed" | "dense"
steps = int(sys.argv[3])
batch = int(sys.argv[4])                 # per-rank rows per step
rank = int(os.environ[ENV_PID])
world = int(os.environ[ENV_NPROC])

n_in = 16
conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
        .list([DenseLayer(n_out=32, activation="tanh"),
               OutputLayer(n_out=3, loss="mcxent", activation="softmax")])
        .set_input_type(InputType.feed_forward(n_in)).build())
net = MultiLayerNetwork(conf).init()
net.set_gradient_sharing(HierarchicalGradientSharing(
    threshold=5e-3, compressed=(mode == "compressed")))

# one deterministic global stream, identical on every rank; each rank
# trains on its strided shard — plain data parallelism across "hosts"
rng = np.random.RandomState(0)
losses = []
for _ in range(steps):
    xg = rng.randn(world * batch, n_in).astype(np.float32)
    labels = (xg[:, 0] > 0).astype(int) + (xg[:, 1] > 0).astype(int)
    yg = np.eye(3, dtype=np.float32)[labels]
    net.fit(xg[rank::world], yg[rank::world])
    losses.append(net.score())

stats = net.gradient_sharing.stats()
np.savez(os.path.join(out_dir, f"curve_{mode}_{rank}.npz"),
         losses=np.asarray(losses, np.float64),
         w0=np.asarray(net.params_["layer_0"]["W"]))
with open(os.path.join(out_dir, f"stats_{mode}_{rank}.json"), "w") as f:
    json.dump(stats, f)
net.set_gradient_sharing(None)           # close the mesh sockets
print(f"rank {rank}/{world}: {mode} x{steps} steps, "
      f"final loss {losses[-1]:.4f}", flush=True)
