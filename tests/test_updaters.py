"""Updater + schedule numerics tests (OpValidation-style, SURVEY.md §4)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.train import (
    AdaDelta, AdaGrad, AdaMax, Adam, AMSGrad, Nadam, Nesterovs, NoOp,
    RmsProp, Sgd, UPDATERS)
from deeplearning4j_tpu.train.schedules import (
    ExponentialSchedule, FixedSchedule, InverseSchedule, MapSchedule,
    PolySchedule, SigmoidSchedule, StepSchedule)
from deeplearning4j_tpu.train.updaters import IUpdater


PARAMS = {"W": jnp.array([[1.0, -2.0], [0.5, 3.0]], jnp.float32),
          "b": jnp.array([0.1, -0.1], jnp.float32)}
GRADS = {"W": jnp.array([[0.1, -0.2], [0.3, 0.4]], jnp.float32),
         "b": jnp.array([0.05, -0.05], jnp.float32)}


@pytest.mark.parametrize("updater", [
    Sgd(0.1), NoOp(), Nesterovs(0.1, momentum=0.9), Adam(1e-3),
    AMSGrad(1e-3), Nadam(1e-3), AdaMax(1e-3), AdaGrad(0.1), RmsProp(0.01),
    AdaDelta()])
def test_updater_runs_and_shapes(updater):
    state = updater.init_state(PARAMS)
    upd, state2 = updater.apply(state, GRADS, 0)
    for k in PARAMS:
        assert upd[k].shape == PARAMS[k].shape
        assert np.all(np.isfinite(np.asarray(upd[k])))
    # second step with evolved state
    upd2, _ = updater.apply(state2, GRADS, 1)
    assert upd2["W"].shape == PARAMS["W"].shape


def test_sgd_exact():
    upd, _ = Sgd(0.5).apply((), GRADS, 0)
    np.testing.assert_allclose(upd["W"], 0.5 * np.asarray(GRADS["W"]), rtol=1e-6)


def test_adam_first_step_closed_form():
    # t=1: m=(1-b1)g, v=(1-b2)g^2, alpha=lr*sqrt(1-b2)/(1-b1)
    # => update = lr * g/|g| ... precisely lr*sign-ish: alpha*m/(sqrt(v)+eps)
    lr, b1, b2, eps = 1e-3, 0.9, 0.999, 1e-8
    upd, _ = Adam(lr, beta1=b1, beta2=b2, epsilon=eps).apply(
        Adam(lr).init_state(PARAMS), GRADS, 0)
    g = np.asarray(GRADS["W"])
    alpha = lr * np.sqrt(1 - b2) / (1 - b1)
    expect = alpha * (1 - b1) * g / (np.sqrt((1 - b2) * g * g) + eps)
    np.testing.assert_allclose(np.asarray(upd["W"]), expect, rtol=1e-5)


def test_nesterovs_cs231n_form():
    mu, lr = 0.9, 0.1
    u = Nesterovs(lr, momentum=mu)
    v0 = u.init_state(PARAMS)
    upd, v1 = u.apply(v0, GRADS, 0)
    g = np.asarray(GRADS["W"])
    v_new = -lr * g  # v0 = 0
    np.testing.assert_allclose(np.asarray(v1["W"]), v_new, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(upd["W"]), -(1 + mu) * v_new, rtol=1e-6)


def test_updater_json_roundtrip():
    for u in [Sgd(0.1), Adam(StepSchedule(0.01, 0.5, 100)), Nesterovs(0.1, momentum=0.8)]:
        d = u.to_json()
        u2 = IUpdater.from_json(d)
        assert type(u2) is type(u)
        upd1, _ = u.apply(u.init_state(PARAMS), GRADS, 5)
        upd2, _ = u2.apply(u2.init_state(PARAMS), GRADS, 5)
        np.testing.assert_allclose(np.asarray(upd1["W"]), np.asarray(upd2["W"]))


def test_schedules():
    assert float(FixedSchedule(0.1).value_at(100)) == pytest.approx(0.1)
    s = StepSchedule(1.0, 0.5, 10)
    assert float(s.value_at(0)) == pytest.approx(1.0)
    assert float(s.value_at(10)) == pytest.approx(0.5)
    assert float(s.value_at(25)) == pytest.approx(0.25)
    e = ExponentialSchedule(1.0, 0.9)
    assert float(e.value_at(2)) == pytest.approx(0.81)
    p = PolySchedule(1.0, 2.0, 100)
    assert float(p.value_at(50)) == pytest.approx(0.25)
    i = InverseSchedule(1.0, 1.0, 1.0)
    assert float(i.value_at(1)) == pytest.approx(0.5)
    m = MapSchedule({0: 0.1, 10: 0.01})
    assert float(m.value_at(5)) == pytest.approx(0.1)
    assert float(m.value_at(15)) == pytest.approx(0.01)
    g = SigmoidSchedule(1.0, 0.5, 10)
    assert float(g.value_at(10)) == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# Second-order solvers (VERDICT §2 Solver/optimize row: LBFGS/CG/line search)
# ---------------------------------------------------------------------------

def _solver_problem():
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    rng = np.random.RandomState(0)
    x = rng.randn(64, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[
        ((x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int))]
    conf = (NeuralNetConfiguration.builder().seed(1)
            .list([DenseLayer(n_out=12, activation="tanh"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init(), x, y


@pytest.mark.parametrize("solver_cls", ["LBFGS", "ConjugateGradient",
                                        "LineGradientDescent"])
def test_solvers_minimize_full_batch(solver_cls):
    import deeplearning4j_tpu.train as T
    net, x, y = _solver_problem()
    s0 = net.score_for(x, y)
    solver = getattr(T, solver_cls)(max_iterations=60)
    final = solver.optimize(net, x, y)
    assert final < s0 * 0.5, (solver_cls, s0, final)
    assert abs(net.score_for(x, y) - final) < 1e-5


def test_lbfgs_beats_few_sgd_steps():
    """LBFGS should reach a much lower full-batch loss than the same budget
    of plain SGD steps — the reason the reference ships it."""
    from deeplearning4j_tpu.train.updaters import Sgd as SgdU
    net, x, y = _solver_problem()
    from deeplearning4j_tpu.train import LBFGS
    lb = LBFGS(max_iterations=40)
    lbfgs_loss = lb.optimize(net, x, y)

    net2, _, _ = _solver_problem()
    for _ in range(40):
        net2.fit(x, y)
    assert lbfgs_loss < net2.score_for(x, y)
