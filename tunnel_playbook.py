"""On-chip validation playbook — run THE MOMENT the axon tunnel is up.

VERDICT r3 #1/#3: every perf claim since round 1 is hardware-unverified,
and the round-3 Pallas kernels (masked flash attention fwd/bwd, fused
LayerNorm) have never been Mosaic-compiled on a real TPU.  This script
runs the whole validation ladder in one go and writes
`bench_artifacts/TUNNEL_VALIDATION.json` incrementally (each stage's
result lands as soon as it finishes, so a tunnel drop mid-run keeps
earlier results).

Stages:
  1. resnet50 headline (bench.py config) + lenet/lstm/bert throughputs
  2. Mosaic compile + correctness of ALL Pallas kernels vs XLA reference
     (flash fwd, flash bwd, masked variants, causal, fused LN fwd/bwd)
  3. flash-vs-XLA A/B at seq {1024, 2048, 4096} (where dispatch engages)
  4. fused-LN vs XLA A/B at BERT shapes
  5. conv-backward layout probes: donate/layout variants of the ResNet
     train step (the 2.3 ms/step retiling-copy lever)

Run: `python tunnel_playbook.py [--quick]`  (expects the axon TPU).
"""
import json
import os
import sys
import time

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "bench_artifacts", "TUNNEL_VALIDATION.json")
RESULTS = {"started": time.strftime("%Y-%m-%d %H:%M:%S"), "stages": {}}


def record(stage, payload):
    RESULTS["stages"][stage] = payload
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(RESULTS, f, indent=2)
    print(f"[playbook] {stage}: {json.dumps(payload)[:300]}", flush=True)


def guard(stage):
    def deco(fn):
        def run(*a, **k):
            try:
                record(stage, fn(*a, **k))
            except Exception as e:
                record(stage, {"error": f"{type(e).__name__}: {e}"[:500]})
        return run
    return deco


def timeit(f, sync, warm=3, n=10):
    for _ in range(warm):
        f()
    sync()
    t0 = time.perf_counter()
    for _ in range(n):
        f()
    sync()
    return (time.perf_counter() - t0) / n


@guard("1_headline")
def stage_headline(quick):
    import jax.numpy as jnp
    from deeplearning4j_tpu.train.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50
    batch = 64
    net = ResNet50(n_classes=1000, input_shape=(224, 224, 3),
                   updater=Nesterovs(0.1, 0.9),
                   compute_dtype="bfloat16").init_model()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, 224, 224, 3).astype(np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.randint(0, 1000, batch)])
    dt = timeit(lambda: net.fit(x, y), lambda: float(net.score()),
                n=5 if quick else 20)
    return {"resnet50_samples_per_sec": round(batch / dt, 1),
            "ms_per_step": round(dt * 1e3, 2)}


@guard("2_mosaic_compile")
def stage_mosaic(quick):
    """First-ever real-TPU compile of every Pallas kernel, checked
    against the XLA reference path."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.attention_kernels import (
        flash_attention_tpu, flash_attention_bwd_tpu)
    from deeplearning4j_tpu.ops.norm_kernels import layer_norm_tpu

    out = {}
    rs = np.random.RandomState(0)
    B, H, T, D = 2, 4, 2048, 64
    q = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32) * 0.1)
    k = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32) * 0.1)
    v = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32) * 0.1)
    mask = jnp.asarray((rs.rand(B, T) > 0.1).astype(np.float32))

    def xla_attn(q, k, v, mask=None, causal=False):
        s = jnp.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
        if mask is not None:
            s = jnp.where(mask[:, None, None, :] > 0, s, -1e30)
        if causal:
            tri = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(tri[None, None], s, -1e30)
        return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(s, -1), v)

    for name, kw in [("plain", {}), ("causal", {"causal": True}),
                     ("masked", {"mask": mask})]:
        got = np.asarray(flash_attention_tpu(q, k, v, **kw)[0]
                         if isinstance(flash_attention_tpu(q, k, v, **kw),
                                       tuple)
                         else flash_attention_tpu(q, k, v, **kw))
        want = np.asarray(xla_attn(q, k, v, **kw))
        err = float(np.max(np.abs(got - want)))
        out[f"flash_fwd_{name}_max_err"] = err
        assert err < 2e-2, (name, err)

    # bwd: compare grads of a scalar loss via the dispatcher-level op
    from deeplearning4j_tpu.ops.attention_kernels import fused_attention

    def loss_fused(q, k, v):
        return jnp.sum(fused_attention(q, k, v, mask=mask) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(xla_attn(q, k, v, mask=mask) ** 2)

    g1 = jax.grad(loss_fused)(q, k, v)
    g2 = jax.grad(loss_xla)(q, k, v)
    out["flash_bwd_masked_max_err"] = float(
        jnp.max(jnp.abs(g1 - g2)))

    # fused LN fwd+bwd
    x = jnp.asarray(rs.randn(4096, 768).astype(np.float32))
    gain = jnp.asarray(rs.rand(768).astype(np.float32) + 0.5)
    bias = jnp.asarray(rs.randn(768).astype(np.float32))

    def ln_ref(x, g, b):
        m = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - m) / jnp.sqrt(var + 1e-5) * g + b

    got = np.asarray(layer_norm_tpu(x, gain, bias, 1e-5)[0])
    want = np.asarray(ln_ref(x, gain, bias))
    out["fused_ln_fwd_max_err"] = float(np.max(np.abs(got - want)))

    from deeplearning4j_tpu.ops.norm_kernels import fused_layer_norm

    def l1(x, g, b):
        return jnp.sum(fused_layer_norm(x, g, b, 1e-5) ** 2)

    def l2(x, g, b):
        return jnp.sum(ln_ref(x, g, b) ** 2)

    ga, gb = jax.grad(l1, (0, 1))(x, gain, bias), \
        jax.grad(l2, (0, 1))(x, gain, bias)
    out["fused_ln_bwd_max_err"] = float(max(
        jnp.max(jnp.abs(a - b)) for a, b in zip(ga, gb)))
    return out


@guard("3_flash_ab")
def stage_flash_ab(quick):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.attention_kernels import flash_attention_tpu
    rs = np.random.RandomState(0)
    out = {}
    for T in ([1024, 2048] if quick else [1024, 2048, 4096]):
        B, H, D = 4, 12, 64
        q = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32) * 0.1)
        k = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32) * 0.1)
        v = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32) * 0.1)

        flash = jax.jit(lambda q, k, v: flash_attention_tpu(q, k, v))

        def xla(q, k, v):
            s = jnp.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
            return jnp.einsum("bhts,bhsd->bhtd",
                              jax.nn.softmax(s, -1), v)

        xla_j = jax.jit(xla)
        r = flash(q, k, v)
        first = r[0] if isinstance(r, tuple) else r
        jax.block_until_ready(first)
        jax.block_until_ready(xla_j(q, k, v))

        def run_flash():
            rr = flash(q, k, v)
            return rr[0] if isinstance(rr, tuple) else rr

        tf_ = timeit(run_flash, lambda: jax.block_until_ready(
            run_flash()), n=10)
        tx = timeit(lambda: xla_j(q, k, v), lambda: jax.block_until_ready(
            xla_j(q, k, v)), n=10)
        out[f"seq{T}"] = {"flash_ms": round(tf_ * 1e3, 3),
                          "xla_ms": round(tx * 1e3, 3),
                          "speedup": round(tx / tf_, 3)}
    return out


@guard("4_ln_ab")
def stage_ln_ab(quick):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.norm_kernels import layer_norm_tpu
    rs = np.random.RandomState(0)
    out = {}
    for rows in [8192, 65536]:
        x = jnp.asarray(rs.randn(rows, 768).astype(np.float32))
        g = jnp.asarray(rs.rand(768).astype(np.float32) + 0.5)
        b = jnp.asarray(rs.randn(768).astype(np.float32))
        fused = jax.jit(lambda x, g, b: layer_norm_tpu(x, g, b,
                                                       1e-5)[0])

        def xla(x, g, b):
            m = jnp.mean(x, -1, keepdims=True)
            v = jnp.var(x, -1, keepdims=True)
            return (x - m) / jnp.sqrt(v + 1e-5) * g + b

        xj = jax.jit(xla)
        jax.block_until_ready(fused(x, g, b))
        jax.block_until_ready(xj(x, g, b))
        tf_ = timeit(lambda: fused(x, g, b),
                     lambda: jax.block_until_ready(fused(x, g, b)))
        tx = timeit(lambda: xj(x, g, b),
                    lambda: jax.block_until_ready(xj(x, g, b)))
        out[f"rows{rows}"] = {"fused_ms": round(tf_ * 1e3, 3),
                              "xla_ms": round(tx * 1e3, 3),
                              "speedup": round(tx / tf_, 3)}
    return out


@guard("5_conv_layout")
def stage_conv_layout(quick):
    """The PERF_ANALYSIS lever: measure the ResNet step with explicit
    donation + input layouts to see how much of the 2.3 ms/step of copy
    time layout control removes."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.train.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50
    batch = 64
    net = ResNet50(n_classes=1000, input_shape=(224, 224, 3),
                   updater=Nesterovs(0.1, 0.9),
                   compute_dtype="bfloat16").init_model()
    rng = np.random.RandomState(0)
    x32 = rng.rand(batch, 224, 224, 3).astype(np.float32)
    y32 = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    out = {}
    # (a) baseline: host f32 features each step (what bench.py times)
    x = jnp.asarray(x32)
    y = jnp.asarray(y32)
    dt = timeit(lambda: net.fit(x, y), lambda: float(net.score()), n=10)
    out["baseline_ms"] = round(dt * 1e3, 2)
    # (b) bf16 features fed directly (halves the input HBM traffic and
    # removes the f32->bf16 convert at the step head)
    xb = jnp.asarray(x32, jnp.bfloat16)
    try:
        dtb = timeit(lambda: net.fit(xb, y), lambda: float(net.score()),
                     n=10)
        out["bf16_inputs_ms"] = round(dtb * 1e3, 2)
    except Exception as e:
        out["bf16_inputs_error"] = str(e)[:200]
    return out


@guard("6_wgrad_ab")
def stage_wgrad_ab(quick):
    """Pallas 3x3 wgrad kernel vs XLA's conv-backward-filter at the
    ResNet-50 block shapes (VERDICT r3 #3: measured table, win or lose).
    Includes the kernel's pad+slice pre-pass in its timing — the honest
    end-to-end cost."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.conv_kernels import (conv3x3_wgrad_tpu,
                                                     conv3x3_wgrad_xla)
    rs = np.random.RandomState(0)
    out = {}
    shapes = [(64, 56, 56, 64, 64), (64, 28, 28, 128, 128),
              (64, 14, 14, 256, 256), (64, 7, 7, 512, 512)]
    for B, H, W, Ci, Co in (shapes[:2] if quick else shapes):
        x = jnp.asarray(rs.randn(B, H, W, Ci).astype(np.float32) * 0.1
                        ).astype(jnp.bfloat16)
        dy = jnp.asarray(rs.randn(B, H, W, Co).astype(np.float32) * 0.1
                         ).astype(jnp.bfloat16)
        pallas_fn = jax.jit(conv3x3_wgrad_tpu)
        xla_fn = jax.jit(conv3x3_wgrad_xla)
        got = pallas_fn(x, dy)
        want = xla_fn(x, dy)
        jax.block_until_ready((got, want))
        err = float(jnp.max(jnp.abs(got - want)))
        tp = timeit(lambda: pallas_fn(x, dy),
                    lambda: jax.block_until_ready(pallas_fn(x, dy)))
        tx = timeit(lambda: xla_fn(x, dy),
                    lambda: jax.block_until_ready(xla_fn(x, dy)))
        out[f"{H}x{W}x{Ci}"] = {
            "pallas_ms": round(tp * 1e3, 3), "xla_ms": round(tx * 1e3, 3),
            "speedup": round(tx / tp, 3), "max_err": err}
    return out


@guard("7_dgrad_ab")
def stage_dgrad_ab(quick):
    """Pallas 3x3 dgrad kernel vs XLA's conv-backward-data at the
    ResNet-50 block shapes (VERDICT r4 #5: wgrad covers only half the
    13.2 ms conv backward).  Includes the pad+views pre-pass in its
    timing — the honest end-to-end cost."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.conv_kernels import (conv3x3_dgrad_tpu,
                                                     conv3x3_dgrad_xla)
    rs = np.random.RandomState(0)
    out = {}
    shapes = [(64, 56, 56, 64, 64), (64, 28, 28, 128, 128),
              (64, 14, 14, 256, 256), (64, 7, 7, 512, 512)]
    for B, H, W, Ci, Co in (shapes[:2] if quick else shapes):
        dy = jnp.asarray(rs.randn(B, H, W, Co).astype(np.float32) * 0.1
                         ).astype(jnp.bfloat16)
        w = jnp.asarray(rs.randn(3, 3, Ci, Co).astype(np.float32) * 0.1
                        ).astype(jnp.bfloat16)
        pallas_fn = jax.jit(conv3x3_dgrad_tpu)
        xla_fn = jax.jit(conv3x3_dgrad_xla)
        got = pallas_fn(dy, w)
        want = xla_fn(dy, w)
        jax.block_until_ready((got, want))
        err = float(jnp.max(jnp.abs(got - want)))
        tp = timeit(lambda: pallas_fn(dy, w),
                    lambda: jax.block_until_ready(pallas_fn(dy, w)))
        tx = timeit(lambda: xla_fn(dy, w),
                    lambda: jax.block_until_ready(xla_fn(dy, w)))
        out[f"{H}x{W}x{Ci}"] = {
            "pallas_ms": round(tp * 1e3, 3), "xla_ms": round(tx * 1e3, 3),
            "speedup": round(tx / tp, 3), "max_err": err}
    return out


@guard("8_conv_bwd_hook_ab")
def stage_conv_hook_ab(quick):
    """End-to-end adoption A/B: the full ResNet-50 train step with the
    Pallas conv-backward hook enabled (wgrad, dgrad, both) vs the XLA
    default.  A measured win flips CONV_BWD_PALLAS's default (or sets
    DL4J_TPU_CONV_BWD_PALLAS); a loss gets this table committed as the
    negative result."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.conv_kernels import CONV_BWD_PALLAS
    from deeplearning4j_tpu.train.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50
    batch = 64
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, 224, 224, 3).astype(np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.randint(0, 1000, batch)])
    out = {}
    for tag, flags in [("xla", {}), ("wgrad", {"wgrad": True}),
                       ("dgrad", {"dgrad": True}),
                       ("both", {"wgrad": True, "dgrad": True})]:
        old = dict(CONV_BWD_PALLAS)
        try:
            CONV_BWD_PALLAS.update(wgrad=False, dgrad=False,
                                   interpret=False)
            CONV_BWD_PALLAS.update(flags)
            net = ResNet50(n_classes=1000, input_shape=(224, 224, 3),
                           updater=Nesterovs(0.1, 0.9),
                           compute_dtype="bfloat16").init_model()
            dt = timeit(lambda: net.fit(x, y),
                        lambda: float(net.score()),
                        n=5 if quick else 10)
            out[tag] = {"ms_per_step": round(dt * 1e3, 2),
                        "samples_per_sec": round(batch / dt, 1)}
        except Exception as e:
            out[tag] = {"error": f"{type(e).__name__}: {e}"[:300]}
        finally:
            CONV_BWD_PALLAS.clear()
            CONV_BWD_PALLAS.update(old)
    return out


@guard("9_fused_dispatch")
def stage_fused_dispatch(quick):
    """Fused k-step dispatch A/B (the ~3 ms/step host-gap lever,
    PERF_ANALYSIS.md r5): per-step fit vs fit_steps(k=10) at b64 vs
    fit_steps(k=4) at b256.  bench.py adopts the fused path by default
    (with per-step fallback); this stage is the measurement behind it."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.train.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50

    rng = np.random.RandomState(0)

    def build():
        return ResNet50(n_classes=1000, input_shape=(224, 224, 3),
                        updater=Nesterovs(0.1, 0.9),
                        compute_dtype="bfloat16").init_model()

    def data(k, b):
        xs = jnp.asarray(rng.rand(k, b, 224, 224, 3).astype(np.float32))
        ys = jnp.asarray(np.eye(1000, dtype=np.float32)[
            rng.randint(0, 1000, (k, b))])
        return xs, ys

    out = {}
    net = build()
    x, y = data(1, 64)
    x, y = x[0], y[0]
    dt = timeit(lambda: net.fit(x, y), lambda: float(net.score()),
                n=5 if quick else 20)
    out["per_step_b64"] = {"ms_per_step": round(dt * 1e3, 2),
                           "samples_per_sec": round(64 / dt, 1)}
    del net

    for tag, k, b, blocks in [("fused_k5_b64", 5, 64, 2 if quick else 6),
                              ("fused_k10_b64", 10, 64, 2 if quick else 4),
                              ("fused_k4_b256", 4, 256, 2 if quick else 3)]:
        try:
            net = build()
            xs, ys = data(k, b)
            t0 = time.time()
            net.fit_steps(xs, ys)
            float(net.score())
            compile_s = round(time.time() - t0, 1)
            dt = timeit(lambda: net.fit_steps(xs, ys),
                        lambda: float(net.score()), warm=0, n=blocks) / k
            out[tag] = {"ms_per_step": round(dt * 1e3, 2),
                        "samples_per_sec": round(b / dt, 1),
                        "compile_s": compile_s}
            del net, xs, ys
        except Exception as e:
            out[tag] = {"error": f"{type(e).__name__}: {e}"[:300]}
    return out


@guard("10_auto_layout")
def stage_auto_layout(quick):
    """AUTO-layout A/B (the 3.1 ms/step retiling-copy lever): compile the
    ResNet step with Layout.AUTO on every input, place params in the
    compiler-preferred layouts, and time vs the default-layout step."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.layout import Format, Layout
    from deeplearning4j_tpu.train.updaters import Nesterovs
    from deeplearning4j_tpu.utils.counters import device_counters
    from deeplearning4j_tpu.zoo import ResNet50

    b = 64
    net = ResNet50(n_classes=1000, input_shape=(224, 224, 3),
                   updater=Nesterovs(0.1, 0.9),
                   compute_dtype="bfloat16").init_model()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(b, 224, 224, 3).astype(np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.randint(0, 1000, b)])
    out = {}
    dt = timeit(lambda: net.fit(x, y), lambda: float(net.score()),
                n=5 if quick else 20)
    out["default_layout_ms"] = round(dt * 1e3, 2)

    body = net._build_step_body()
    it_dev, ep_dev = device_counters(net)
    args = (net.params_, net.state_, net.opt_state_, {"input": x}, [y],
            None, net._rng, it_dev, ep_dev)
    auto = Format(Layout.AUTO)
    fmt_tree = jax.tree_util.tree_map(lambda _: auto, args)
    step = jax.jit(body, donate_argnums=(0, 1, 2), in_shardings=fmt_tree)
    compiled = step.lower(*args).compile()
    placed = jax.tree_util.tree_map(jax.device_put, args,
                                    compiled.input_formats)
    x_p, y_p, ep_p = placed[3], placed[4], placed[8]
    p, s2, o, loss, r, it = compiled(*placed)
    jax.block_until_ready(loss)
    n = 5 if quick else 20
    t0 = time.perf_counter()
    for _ in range(n):
        p, s2, o, loss, r, it = compiled(p, s2, o, x_p, y_p, None, r, it,
                                         ep_p)
    float(loss)
    out["auto_layout_ms"] = round((time.perf_counter() - t0) / n * 1e3, 2)
    return out


@guard("11_pool_bwd")
def stage_pool_bwd(quick):
    """Taps max-pool backward vs XLA select-and-scatter (0.88 ms/step in
    the r5 profile): isolated at the ResNet stem shape, then the full
    train step with POOL_BWD_TAPS on.  Win → flip the flag default;
    loss → commit the table."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from deeplearning4j_tpu.ops.pool_kernels import (POOL_BWD_TAPS,
                                                     max_pool2d_taps)
    from deeplearning4j_tpu.train.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50

    out = {}
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(64, 112, 112, 64).astype(np.bfloat16))

    def pool_xla(a):
        return lax.reduce_window(a, -jnp.inf, lax.max, (1, 3, 3, 1),
                                 (1, 2, 2, 1), "SAME")

    t = pool_xla(x) * 0.9
    g_xla = jax.jit(jax.grad(lambda a: jnp.sum((pool_xla(a) - t) ** 2)))
    g_tap = jax.jit(jax.grad(lambda a: jnp.sum(
        (max_pool2d_taps(a, (3, 3), (2, 2), "SAME") - t) ** 2)))
    r = g_xla(x); jax.block_until_ready(r)
    r2 = g_tap(x); jax.block_until_ready(r2)
    out["isolated_max_err"] = float(jnp.max(jnp.abs(
        r.astype(jnp.float32) - r2.astype(jnp.float32))))
    n = 10 if quick else 30
    t0 = time.perf_counter()
    for _ in range(n):
        r = g_xla(x)
    jax.block_until_ready(r)
    out["isolated_xla_ms"] = round((time.perf_counter() - t0) / n * 1e3, 3)
    t0 = time.perf_counter()
    for _ in range(n):
        r2 = g_tap(x)
    jax.block_until_ready(r2)
    out["isolated_taps_ms"] = round((time.perf_counter() - t0) / n * 1e3, 3)

    batch = 64
    xb = jnp.asarray(rng.rand(batch, 224, 224, 3).astype(np.float32))
    yb = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.randint(0, 1000, batch)])
    for tag, flag in [("step_xla", False), ("step_taps", True)]:
        old = dict(POOL_BWD_TAPS)
        try:
            POOL_BWD_TAPS["enabled"] = flag
            net = ResNet50(n_classes=1000, input_shape=(224, 224, 3),
                           updater=Nesterovs(0.1, 0.9),
                           compute_dtype="bfloat16").init_model()
            dt = timeit(lambda: net.fit(xb, yb),
                        lambda: float(net.score()), n=5 if quick else 15)
            out[tag] = {"ms_per_step": round(dt * 1e3, 2),
                        "samples_per_sec": round(batch / dt, 1)}
        except Exception as e:
            out[tag] = {"error": f"{type(e).__name__}: {e}"[:300]}
        finally:
            POOL_BWD_TAPS.clear()
            POOL_BWD_TAPS.update(old)
    return out


def main():
    quick = "--quick" in sys.argv
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from __graft_entry__ import _probe_backend_device_count
    n = _probe_backend_device_count()
    if n < 1:
        print("[playbook] backend unreachable — aborting", flush=True)
        record("0_probe", {"devices": 0})
        sys.exit(1)
    import jax
    record("0_probe", {"devices": n,
                       "platform": jax.default_backend()})
    stage_headline(quick)
    stage_mosaic(quick)
    stage_flash_ab(quick)
    stage_ln_ab(quick)
    stage_conv_layout(quick)
    stage_wgrad_ab(quick)
    stage_dgrad_ab(quick)
    stage_conv_hook_ab(quick)
    stage_fused_dispatch(quick)
    stage_auto_layout(quick)
    stage_pool_bwd(quick)
    print("[playbook] DONE", flush=True)


if __name__ == "__main__":
    main()
